"""Serving microbenchmark: serialized-lock baseline vs dynamic batcher,
plus (``--fleet``) the multi-replica fleet leg.

The fleet leg (PR 9) measures the serving TIER, not one server: real
replica subprocesses (each its own interpreter + XLA runtime — no
shared GIL) behind the in-process router (serving/router.py):

 - aggregate closed-loop ``:predict`` throughput, 1 replica vs 3
   replicas behind the router, as interleaved timed blocks;
 - a fleet hot-swap fired MID-STORM: a new export version rolls out
   through the coordinator's barrier while keyed clients hammer —
   reported: dropped requests (must be 0) and mixed-version pairs
   (a version regression for one key; must be 0);
 - PS-backed ``:lookup``: a table served straight from a live PS shard
   (never exported to disk), verified bit-identical to the
   exported-table path, with the hot-row-cache hit ratio scraped off
   the replica's /metrics.

Each replica is pinned to ONE core via taskset (the cpuset a
per-container CPU limit would impose) in BOTH legs, so the 1-vs-3
ratio measures fleet fan-out, not XLA intra-op threading — and the
result JSON carries the rig's physical-core scaling ceiling, because a
2-core box cannot express 3-replica scaling no matter how good the
router is (the headline regime needs >= 4 cores or one host per
replica).

The original single-server comparison (default mode):

Closed-loop concurrent clients (next request only after the previous
response) hammer ``:predict`` on two endpoints over the SAME export:

 - ``serialized``: batching disabled — every request takes the
   per-model execution lock and dispatches its own ``exported.call``
   (the pre-batcher server behavior);
 - ``batched``: the dynamic micro-batcher (serving/batcher.py)
   coalesces concurrent requests into bucketed padded device batches.

Two measurement layers, both reported:

 - ``endpoint``: clients call ``ModelEndpoint.predict`` directly — the
   serving hot path this PR changes (marshalling, admission queue,
   device execution), without the HTTP shell.  The headline ratio.
 - ``http``: end-to-end over real keep-alive HTTP connections.  On
   this single-core rig the client+server JSON/HTTP CPU — identical in
   both modes and GIL-serialized with everything else — dominates, so
   the end-to-end ratio understates the device-path win; reported
   honestly alongside.

Each pair runs as INTERLEAVED timed blocks (A,B,A,B,... best block
kept per mode, the BENCHMARKS.md convention): this container is
shared, so wall-clock noise between back-to-back runs exceeds the
effect under test, and pairing decorrelates it.  Before timing, one
canonical request is sent through both modes and compared — the
batcher must be numerically identical, not just faster.

The model is CTR-ranking shaped (small dense feature vector, small
MLP): per-request device work is tiny, so the serialized path is
dispatch-bound — exactly the regime request batching exists for.
"""

import http.client
import json
import os
import tempfile
import threading
import time

_PLATFORM = os.environ.get("ELASTICDL_TPU_PLATFORM") or "cpu"
os.environ["ELASTICDL_TPU_PLATFORM"] = _PLATFORM
os.environ["JAX_PLATFORMS"] = _PLATFORM

import numpy as np  # noqa: E402

from elasticdl_tpu.client import frame_client as fc  # noqa: E402
from elasticdl_tpu.utils import hist as hist_mod  # noqa: E402
from elasticdl_tpu.utils import tensor_codec as tc  # noqa: E402

FEATURES = 64
HIDDEN = 128
CLASSES = 8
# max_batch_size matches the benched concurrency: a complete wave of
# in-flight requests size-flushes the instant it is assembled instead
# of burning the residual batch window (docs/serving.md tuning notes —
# cap at the live concurrency you provision for).
MAX_BATCH = 8
TIMEOUT_MS = 20.0
REQUESTS_PER_CLIENT = 60
BLOCKS = 4
CONCURRENCY = (1, 8, 16)
HEADLINE_CONCURRENCY = 8  # the acceptance level; 16 reported too


def _export_mlp(export_dir):
    from elasticdl_tpu.serving.export import export_servable

    rng = np.random.RandomState(0)
    params = {
        "w1": rng.randn(FEATURES, HIDDEN).astype(np.float32) * 0.05,
        "b1": np.zeros(HIDDEN, np.float32),
        "w2": rng.randn(HIDDEN, HIDDEN).astype(np.float32) * 0.05,
        "b2": np.zeros(HIDDEN, np.float32),
        "w3": rng.randn(HIDDEN, CLASSES).astype(np.float32) * 0.05,
        "b3": np.zeros(CLASSES, np.float32),
    }

    def apply_fn(p, x):
        import jax.numpy as jnp

        h = jnp.maximum(x @ p["w1"] + p["b1"], 0.0)
        h = jnp.maximum(h @ p["w2"] + p["b2"], 0.0)
        return h @ p["w3"] + p["b3"]

    export_servable(
        export_dir, apply_fn, params,
        np.zeros((1, FEATURES), np.float32),
        model_name="mlp", platforms=("cpu",),
    )


def _payload(idx, rows=1):
    return {"instances": [[float((idx * 37 + r + j) % 23) / 23.0
                           for j in range(FEATURES)]
                          for r in range(rows)]}


class _Rig:
    """One endpoint (+ HTTP server) per mode; collects best-block
    wall times and latency distributions per (layer, concurrency)."""

    def __init__(self, export_dir, batching, payload_rows=1):
        from elasticdl_tpu.serving.server import (
            ModelEndpoint,
            build_server,
        )

        self.label = "batched" if batching is not None else "serialized"
        self.payload_rows = payload_rows
        self.endpoint = ModelEndpoint(export_dir, batching=batching)
        self.server = build_server(self.endpoint, port=0)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.best = {}       # (layer, conc) -> best wall seconds
        self.latencies = {}  # (layer, conc) -> best block's latencies
        self.counters = {}   # (layer, conc) -> /statz counters snapshot

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.endpoint.close()

    def predict_http_once(self, payload):
        conn = http.client.HTTPConnection("127.0.0.1", self.port,
                                          timeout=60)
        try:
            conn.request("POST", "/v1/models/mlp:predict",
                         body=json.dumps(payload))
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()[:500]
            return json.loads(resp.read())["predictions"]
        finally:
            conn.close()

    def timed_block(self, layer, concurrency, requests_per_client):
        self.endpoint.timing.reset()  # per-block counters
        barrier = threading.Barrier(concurrency + 1)
        latencies = [[] for _ in range(concurrency)]
        errors = []

        def endpoint_client(idx):
            body = _payload(idx, self.payload_rows)
            try:
                self.endpoint.predict(body)  # unmeasured warm request
                barrier.wait()
                for _ in range(requests_per_client):
                    t0 = time.perf_counter()
                    self.endpoint.predict(body)
                    latencies[idx].append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 — fail loudly, not
                # by hanging the barrier.
                errors.append(repr(e))
                barrier.abort()

        def http_client(idx):
            body = json.dumps(_payload(idx, self.payload_rows))
            conn = http.client.HTTPConnection(
                "127.0.0.1", self.port, timeout=120)
            try:
                conn.request("POST", "/v1/models/mlp:predict",
                             body=body)
                conn.getresponse().read()  # warm: connection + state
                barrier.wait()
                for _ in range(requests_per_client):
                    t0 = time.perf_counter()
                    conn.request("POST", "/v1/models/mlp:predict",
                                 body=body)
                    resp = conn.getresponse()
                    raw = resp.read()
                    if resp.status != 200:
                        errors.append(raw[:200])
                        return
                    json.loads(raw)
                    latencies[idx].append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                barrier.abort()
            finally:
                conn.close()

        def http_bin_client(idx):
            # The binary wire path through the frame client SDK
            # (client/frame_client.py) — the same keep-alive
            # connection discipline as the JSON client, one pooled
            # connection per thread.  Work parity with the JSON leg:
            # encode once outside the loop (predict_frame replays the
            # blob), decode every response into typed arrays.
            x = np.asarray(_payload(idx, self.payload_rows)
                           ["instances"], np.float32)
            body = fc.encode_predict(x)
            client = fc.FrameClient("127.0.0.1:%d" % self.port,
                                    timeout=120, pool_size=1)
            try:
                client.predict_frame("mlp", body)  # warm
                barrier.wait()
                for _ in range(requests_per_client):
                    t0 = time.perf_counter()
                    frame = client.predict_frame("mlp", body)
                    fc.decode_predictions(frame)
                    latencies[idx].append(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                barrier.abort()
            finally:
                client.close()

        target = {"endpoint": endpoint_client,
                  "http": http_client,
                  "http_bin": http_bin_client}[layer]
        threads = [threading.Thread(target=target, args=(i,),
                                    daemon=True)
                   for i in range(concurrency)]
        for t in threads:
            t.start()
        try:
            barrier.wait()
        except threading.BrokenBarrierError:
            pass  # a client aborted pre-barrier; errors raise below
        start = time.perf_counter()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start
        if errors:
            raise RuntimeError("client errors: %s" % errors[:3])
        key = (layer, concurrency)
        if key not in self.best or elapsed < self.best[key]:
            self.best[key] = elapsed
            self.latencies[key] = [
                x for per_client in latencies for x in per_client]
            self.counters[key] = self.endpoint.stats()
        return elapsed

    def result(self, layer, concurrency, requests_per_client):
        key = (layer, concurrency)
        lats = np.asarray(sorted(self.latencies[key]))
        total = concurrency * requests_per_client
        stats = self.counters[key]
        counters = stats["counters"]
        return {
            "mode": self.label,
            "layer": layer,
            "concurrency": concurrency,
            "requests": total,
            "requests_per_sec": round(total / self.best[key], 1),
            "p50_ms": round(1e3 * float(np.percentile(lats, 50)), 2),
            "p99_ms": round(1e3 * float(np.percentile(lats, 99)), 2),
            "mean_batch_occupancy": stats["mean_batch_occupancy"],
            "padded_rows": counters.get("batcher.padded_rows", 0),
            "size_flushes": counters.get("batcher.size_flushes", 0),
            "timeout_flushes": counters.get(
                "batcher.timeout_flushes", 0),
            "empty_flushes": counters.get("batcher.empty_flushes", 0),
        }


# -- fleet leg (PR 9) --------------------------------------------------

FLEET_FEATURES = 64
FLEET_HIDDEN = 1024
FLEET_ROWS_PER_REQUEST = 64
FLEET_CONCURRENCY = 6
FLEET_REQUESTS_PER_CLIENT = 20
FLEET_BLOCKS = 3


def _free_port():
    import socket

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def _export_fleet_version(base, version, bias=0.0):
    """A compute-heavier MLP than the batching leg's: per-request
    device time must dominate the HTTP/JSON shell so the fleet ratio
    measures replicated EXECUTION, not the bench process's client
    CPU."""
    from elasticdl_tpu.serving.export import export_servable

    rng = np.random.RandomState(7)
    params = {
        "w1": rng.randn(FLEET_FEATURES, FLEET_HIDDEN)
        .astype(np.float32) * 0.03,
        "w2": rng.randn(FLEET_HIDDEN, FLEET_HIDDEN)
        .astype(np.float32) * 0.03,
        "w3": rng.randn(FLEET_HIDDEN, CLASSES).astype(np.float32)
        * 0.03,
    }

    def apply_fn(p, x):
        import jax.numpy as jnp

        h = jnp.maximum(x @ p["w1"], 0.0)
        h = jnp.maximum(h @ p["w2"], 0.0)
        return h @ p["w3"] + bias

    export_servable(
        os.path.join(base, str(version)), apply_fn, params,
        np.zeros((1, FLEET_FEATURES), np.float32),
        model_name="mlp", version=version, platforms=("cpu",),
    )


def _spawn_replica(base, port, ps_addrs="", cpu=None):
    import shutil
    import subprocess
    import sys

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "ELASTICDL_TPU_PLATFORM": "cpu",
        "OMP_NUM_THREADS": "1",
        "OPENBLAS_NUM_THREADS": "1",
    })
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.serving.server",
        "--export_dir", base, "--host", "127.0.0.1",
        "--port", str(port), "--fleet_managed", "true",
        "--max_batch_size", str(MAX_BATCH),
        "--batch_timeout_ms", "5",
    ]
    if cpu is not None and shutil.which("taskset"):
        # One core per replica (the cpuset a per-container CPU limit
        # would impose): XLA's intra-op pool otherwise grabs every
        # visible core for ONE replica's matmuls, so the 1-vs-3 ratio
        # would measure intra-op threading, not fleet fan-out.
        cmd = ["taskset", "-c", str(cpu)] + cmd
    if ps_addrs:
        cmd += ["--ps_addrs", ps_addrs]
    return subprocess.Popen(cmd, env=env)


def _wait_http_ok(port, path="/healthz", timeout=90):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=2)
            conn.request("GET", path)
            ok = conn.getresponse().status == 200
            conn.close()
            if ok:
                return True
        except OSError:
            time.sleep(0.2)
    return False


class _Fleet:
    """N replica subprocesses behind an in-process router."""

    def __init__(self, base, n, ps_addrs=""):
        from elasticdl_tpu.serving.router import (
            Router,
            build_router_server,
        )

        n_cpus = len(os.sched_getaffinity(0))
        self.procs = []
        addrs = []
        for i in range(n):
            port = _free_port()
            self.procs.append(_spawn_replica(
                base, port, ps_addrs=ps_addrs, cpu=i % n_cpus))
            addrs.append("127.0.0.1:%d" % port)
        for addr in addrs:
            assert _wait_http_ok(int(addr.rpartition(":")[2])), (
                "replica %s did not come up" % addr)
        self.router = Router(addrs, export_dir=base,
                             probe_interval=0.25, poll_interval=1.0,
                             barrier_timeout=120.0)
        self.server = build_router_server(self.router, port=0)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.router.start(coordinate=True)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            status = self.router.fleet_status()
            healthy = sum(1 for r in status["replicas"].values()
                          if r["healthy"])
            if healthy == n and status["committed_version"] >= 1:
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("fleet did not become healthy: %s"
                               % self.router.fleet_status())

    def replica_metrics(self):
        out = []
        for addr in list(self.router.state.snapshot()[0]):
            port = int(addr.rpartition(":")[2])
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=5)
            conn.request("GET", "/metrics")
            out.append(conn.getresponse().read().decode())
            conn.close()
        return out

    def close(self):
        import signal as _signal

        self.router.stop()
        self.server.shutdown()
        self.server.server_close()
        for proc in self.procs:
            if proc.poll() is None:
                proc.send_signal(_signal.SIGTERM)  # graceful drain
        deadline = time.monotonic() + 15
        for proc in self.procs:
            while proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.1)
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def _fleet_storm(port, concurrency, requests_per_client, keyed=False,
                 payload_rows=FLEET_ROWS_PER_REQUEST):
    """Closed-loop keep-alive clients against the router.  Returns
    (elapsed_secs, ok_count, error_list, per_key_versions)."""
    barrier = threading.Barrier(concurrency + 1)
    errors = []
    versions = {}

    def client(idx):
        body = {"instances": [[float((idx * 31 + j) % 17) / 17.0
                               for j in range(FLEET_FEATURES)]
                              for _ in range(payload_rows)]}
        if keyed:
            body["routing_key"] = "storm-%d" % idx
        raw = json.dumps(body)
        seen = versions.setdefault(idx, [])
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=120)
        try:
            conn.request("POST", "/v1/models/mlp:predict", body=raw)
            resp = conn.getresponse()
            resp.read()  # warm: connection + replica state
            if resp.status != 200:
                errors.append("warm: %d" % resp.status)
                barrier.abort()
                return
            barrier.wait()
            for _ in range(requests_per_client):
                conn.request("POST", "/v1/models/mlp:predict",
                             body=raw)
                resp = conn.getresponse()
                payload = resp.read()
                if resp.status != 200:
                    errors.append((resp.status, payload[:200]))
                    return
                if keyed:
                    seen.append(json.loads(payload)["model_version"])
                else:
                    # Throughput blocks: don't burn bench-process GIL
                    # decoding payloads — status checked, bytes read.
                    seen.append(0)
        except threading.BrokenBarrierError:
            pass
        except Exception as e:  # noqa: BLE001 — a dropped request IS
            # the failure the fleet drill counts
            errors.append(repr(e))
        finally:
            conn.close()

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(concurrency)]
    for t in threads:
        t.start()
    try:
        barrier.wait()
    except threading.BrokenBarrierError:
        pass
    start = time.perf_counter()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - start
    ok = sum(len(v) for v in versions.values())
    return elapsed, ok, errors, versions


def _run_fleet_throughput(base, requests_per_client):
    """Interleaved 1-replica vs 3-replica blocks.  The headline ratio
    is the MEDIAN of per-block ratios (the bench_zero idiom): each
    block pairs the two fleets back-to-back, so the shared container's
    CPU-steal noise — which far exceeds the effect at this core count —
    cancels within a pair instead of corrupting a best-of comparison
    across instants."""
    rates = {1: [], 3: []}
    fleets = {1: _Fleet(base, 1), 3: _Fleet(base, 3)}
    try:
        for block in range(FLEET_BLOCKS):
            # Alternate leg order per block to cancel warmup drift.
            order = [1, 3] if block % 2 == 0 else [3, 1]
            for n in order:
                elapsed, ok, errors, _ = _fleet_storm(
                    fleets[n].port, FLEET_CONCURRENCY,
                    requests_per_client)
                if errors:
                    raise RuntimeError("fleet-%d errors: %s"
                                       % (n, errors[:3]))
                rates[n].append(ok / elapsed)
        # Hot-swap drill on the 3-replica fleet, mid-storm.
        drill = _run_hotswap_drill(base, fleets[3])
    finally:
        for fleet in fleets.values():
            fleet.close()
    ratios = sorted(r3 / r1 for r1, r3 in zip(rates[1], rates[3]))
    median_ratio = ratios[len(ratios) // 2]
    return ({n: round(max(r), 1) for n, r in rates.items()},
            round(median_ratio, 2), drill)


def _run_hotswap_drill(base, fleet):
    """Fire a new export version mid-storm; count drops and
    mixed-version (per-key regression) pairs."""
    swap_result = {}

    def swap():
        time.sleep(1.0)  # let the storm establish
        _export_fleet_version(base, 2, bias=1.0)
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if fleet.router.coordinator.committed_version == 2:
                swap_result["committed"] = True
                return
            time.sleep(0.1)
        swap_result["committed"] = False

    swapper = threading.Thread(target=swap, daemon=True)
    swapper.start()
    elapsed, ok, errors, versions = _fleet_storm(
        fleet.port, FLEET_CONCURRENCY, FLEET_REQUESTS_PER_CLIENT * 3,
        keyed=True)
    swapper.join(timeout=120)
    mixed = 0
    straddled = 0
    for _key, seen in versions.items():
        if seen != sorted(seen):
            mixed += 1
        if seen and seen[0] == 1 and seen[-1] == 2:
            straddled += 1
    return {
        "committed": swap_result.get("committed", False),
        "requests": ok,
        "dropped_or_errored": len(errors),
        "mixed_version_keys": mixed,
        "keys_straddling_flip": straddled,
        "storm_secs": round(elapsed, 1),
    }


def _run_ps_lookup_leg(tmp):
    """A table served straight from a live PS shard — never exported —
    bit-identical to the exported-table path, hit ratio on /metrics."""
    from elasticdl_tpu.proto import rpc
    from elasticdl_tpu.ps.optimizer import create_optimizer
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.utils import grpc_utils
    from elasticdl_tpu.worker.ps_client import PSClient

    servicer = PserverServicer(
        Parameters(), create_optimizer("sgd", "learning_rate=0.1"),
        generation=1)
    ps_server = grpc_utils.build_server(max_workers=8)
    rpc.add_pserver_servicer(servicer, ps_server)
    ps_port = ps_server.add_insecure_port("[::]:0")
    ps_server.start()
    channel = grpc_utils.build_channel("localhost:%d" % ps_port)
    grpc_utils.wait_for_channel_ready(channel)
    seed_client = PSClient([channel])
    n_rows, dim = 4096, 16
    seed_client.push_model({}, embedding_infos=[
        {"name": "users", "dim": dim, "initializer": "uniform"}])
    trained = seed_client.pull_embedding_vectors(
        "users", np.arange(n_rows))

    base = os.path.join(tmp, "lookup_exports")
    # The export embeds a COPY of the table under another name; "users"
    # itself is never exported — it serves from the PS.
    export_servable(
        os.path.join(base, "1"),
        lambda p, x: x @ p["w"],
        {"w": np.zeros((2, 2), np.float32)},
        np.zeros((1, 2), np.float32), model_name="mlp", version=1,
        embeddings={"users_copy": (np.arange(n_rows), trained)},
        platforms=("cpu",),
    )
    port = _free_port()
    proc = _spawn_replica(base, port,
                          ps_addrs="localhost:%d" % ps_port)
    try:
        assert _wait_http_ok(port)
        rng = np.random.RandomState(11)
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=60)
        identical = True
        lookups = 0
        t0 = time.perf_counter()
        for _ in range(200):
            # Zipf-ish id mix: a hot head + a long tail, the access
            # pattern the hot-row LRU exists for.
            ids = np.concatenate([
                rng.randint(0, 64, 48),
                rng.randint(0, n_rows, 16),
            ]).tolist()
            out = {}
            for table in ("users", "users_copy"):
                conn.request("POST", "/v1/models/mlp:lookup",
                             body=json.dumps({"table": table,
                                              "ids": ids}))
                resp = conn.getresponse()
                payload = json.loads(resp.read())
                assert resp.status == 200, payload
                out[table] = (payload["source"],
                              np.asarray(payload["vectors"],
                                         np.float32))
            assert out["users"][0] == "ps"
            assert out["users_copy"][0] == "export"
            identical = identical and bool(np.array_equal(
                out["users"][1], out["users_copy"][1]))
            lookups += 1
        lookup_secs = time.perf_counter() - t0
        conn.request("GET", "/metrics")
        metrics = conn.getresponse().read().decode()
        conn.close()
        hit_ratio = None
        for line in metrics.splitlines():
            if line.startswith(
                    "elasticdl_serving_emb_cache_hit_ratio"):
                hit_ratio = float(line.rsplit(" ", 1)[1])
        return {
            "bit_identical_to_export_path": identical,
            "lookups": lookups,
            "lookups_per_sec": round(lookups / lookup_secs, 1),
            "emb_cache_hit_ratio": hit_ratio,
            "table_rows_served_from_ps": n_rows,
        }
    finally:
        if proc.poll() is None:
            proc.terminate()
            proc.wait()
        ps_server.stop(grace=None)


def run_fleet_bench(requests_per_client=FLEET_REQUESTS_PER_CLIENT):
    n_cpus = len(os.sched_getaffinity(0))
    with tempfile.TemporaryDirectory() as tmp:
        base = os.path.join(tmp, "fleet_exports")
        _export_fleet_version(base, 1)
        throughput, ratio, drill = _run_fleet_throughput(
            base, requests_per_client)
        lookup = _run_ps_lookup_leg(tmp)
    # With R replicas pinned one-core-each, aggregate scaling is
    # hard-capped by the physical core count — and the router + the
    # closed-loop clients (one shared process here) compete for the
    # SAME cores, so a 2-core rig cannot reach even 2x at any replica
    # count.  Reported so the number can't be read as a fleet defect.
    ceiling = round(min(3.0, float(n_cpus)), 2)
    print(json.dumps({
        "metric": "serving_fleet_throughput",
        "value": ratio,
        "unit": "x aggregate predict throughput (3 replicas vs 1 "
                "behind the router, %d closed-loop clients, %d-row "
                "requests, median of per-block ratios)"
                % (FLEET_CONCURRENCY, FLEET_ROWS_PER_REQUEST),
        "vs_baseline": None,
        "detail": {
            "best_requests_per_sec_by_replicas": {
                str(n): rps for n, rps in sorted(throughput.items())},
            "hotswap_drill": drill,
            "ps_lookup_leg": lookup,
            "replicas_are_subprocesses": True,
            "cpuset": "one core per replica via taskset (a "
                      "per-container CPU limit); router + clients "
                      "share the same %d cores" % n_cpus,
            "n_cpus": n_cpus,
            "aggregate_scaling_ceiling_x": ceiling,
            "baseline": "self-relative: 1 replica behind the same "
                        "router IS the baseline; the 3-vs-1 regime "
                        "this tier targets (each replica + the router "
                        "on its own host/core) needs >= 4 cores",
        },
    }))
    return ratio, drill, lookup


# -- binary wire leg (the zero-copy data plane) -------------------------

WIRE_CONCURRENCY = 16       # the acceptance level (ROADMAP item 5)
WIRE_APPROACH_FLOOR = 0.75  # e2e ratio must be >= 75% of endpoint's
WIRE_P99_SLACK = 1.10       # binary p99 may not exceed json p99 by >10%
# Requests carry a realistic ranking-candidate slate (the fleet leg's
# 64-row shape), not one row — marshal cost scales with rows (the
# whole point of the binary plane) while the per-request stdlib-HTTP
# overhead (identical in both modes, the irreducible transport floor)
# amortizes.  The batch cap fits 8 such requests per executed batch.
WIRE_ROWS = 64
WIRE_MAX_BATCH = 512


def _hist_p99_ms(stats):
    snap = (stats.get("hists") or {}).get("serving.request")
    if not snap or not snap.get("count"):
        return None
    return round(1e3 * hist_mod.quantile(snap, 0.99), 3)


def _run_router_passthrough(rig):
    """One keyed binary request direct vs through the router: the
    forwarded RESPONSE must be byte-identical (zero re-encode on the
    proxied body; the request side's byte-identity is pinned with a
    capturing replica in tests/test_serving_binary.py)."""
    from elasticdl_tpu.serving.router import (
        Router,
        build_router_server,
    )

    x = np.asarray(_payload(5)["instances"], np.float32)
    blob = fc.encode_predict(x, routing_key="bench-key")

    def post(port):
        # roundtrip (not predict_frame): the check compares RAW reply
        # bytes, which the typed surface would decode away.
        with fc.FrameClient("127.0.0.1:%d" % port,
                           timeout=60) as client:
            status, _ctype, raw = client.roundtrip(
                "/v1/models/mlp:predict", blob)
            return status, raw

    router = Router(["127.0.0.1:%d" % rig.port], probe_interval=0.2)
    router.start()
    server = build_router_server(router, port=0)
    threading.Thread(target=server.serve_forever,
                     daemon=True).start()
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if router.state.routable(None):
                break
            time.sleep(0.05)
        direct_status, direct = post(rig.port)
        routed_status, routed = post(server.server_address[1])
        return {
            "direct_status": direct_status,
            "routed_status": routed_status,
            "byte_identical_response": bool(direct == routed),
        }
    finally:
        router.stop()
        server.shutdown()
        server.server_close()


def _run_frame_transfer_leg(blocks=5):
    """The streaming export/ingest sub-leg: ONE model payload through
    the npz archive path (what every publish used to round-trip) vs
    the binary model frame (encode -> decode as zero-copy views),
    interleaved, best-of per mode."""
    from elasticdl_tpu.serving.export import _npz_bytes, decode_payload

    rng = np.random.RandomState(0)
    payload = {"layer%02d/w" % i: rng.randn(256, 256)
               .astype(np.float32) for i in range(16)}
    payload["emb_ids/users"] = np.arange(20000, dtype=np.int64)
    payload["emb_vals/users"] = rng.randn(20000, 32)\
        .astype(np.float32)
    nbytes = sum(a.nbytes for a in payload.values())

    import io as _io

    def npz_pass():
        blob = _npz_bytes(payload)
        with np.load(_io.BytesIO(blob)) as z:
            dense, emb = decode_payload(
                {key: z[key] for key in z.files})
        return dense, emb

    def frame_pass():
        blob = tc.encode_frame(payload, kind="servable")
        frame = tc.decode_frame(blob)
        return decode_payload(dict(frame.tensors))

    best = {"npz": float("inf"), "frame": float("inf")}
    for block in range(blocks):
        order = (("npz", npz_pass), ("frame", frame_pass)) \
            if block % 2 == 0 else (("frame", frame_pass),
                                    ("npz", npz_pass))
        for name, fn in order:
            t0 = time.perf_counter()
            dense, emb = fn()
            best[name] = min(best[name], time.perf_counter() - t0)
    assert set(dense) and "users" in emb  # both paths decoded fully
    return {
        "payload_mb": round(nbytes / 1e6, 1),
        "npz_roundtrip_ms": round(1e3 * best["npz"], 1),
        "frame_roundtrip_ms": round(1e3 * best["frame"], 1),
        "frame_speedup_x": round(best["npz"] / best["frame"], 2),
    }


def run_wire_bench(requests_per_client, max_batch_size,
                   batch_timeout_ms, blocks=BLOCKS):
    """The binary-plane acceptance leg: batched-vs-serialized ratios
    at THREE layers (endpoint, http+JSON, http+binary) as interleaved
    blocks, then the gates the ISSUE/ROADMAP name:

      1. the binary e2e ratio at c=16 must be within 25% of the
         endpoint-layer ratio (the JSON e2e ratio historically halved
         it — that dilution is what this data plane removes);
      2. binary server-side request p99 (the PR-13
         ``serving.request`` histogram) must not exceed the JSON
         path's by more than 10%;
      3. JSON and binary responses bit-identical on the same model;
      4. the router forwards binary bodies byte-identically.
    """
    conc = WIRE_CONCURRENCY
    with tempfile.TemporaryDirectory() as tmp:
        export_dir = os.path.join(tmp, "export")
        _export_mlp(export_dir)
        from elasticdl_tpu.serving.batcher import BatchConfig

        serialized = _Rig(export_dir, None,
                          payload_rows=WIRE_ROWS)
        batched = _Rig(export_dir, BatchConfig(
            max_batch_size=max_batch_size or WIRE_MAX_BATCH,
            batch_timeout_ms=batch_timeout_ms),
            payload_rows=WIRE_ROWS)
        try:
            # Bit-identity gate before any timing: JSON vs binary on
            # the SAME batched server.
            probe = _payload(3, WIRE_ROWS)
            probe["instances"] = probe["instances"] * 3
            want = np.asarray(batched.predict_http_once(probe),
                              np.float32)
            with fc.FrameClient("127.0.0.1:%d" % batched.port,
                                timeout=60) as probe_client:
                got = probe_client.predict(
                    "mlp", np.asarray(probe["instances"],
                                      np.float32))
            identical = bool(np.array_equal(want, got))
            if not identical:
                raise SystemExit("binary predictions differ from JSON")

            # Interleaved blocks with leg-order alternation; the
            # gate ratios come from each leg's BEST block (the PR-3
            # idiom): container steal/scheduling noise is strictly
            # one-sided (it only ever slows a leg), so best-of-N is
            # the consistent estimator of each leg's capability —
            # medians of the 16-threads-on-2-cores endpoint legs
            # measured +/-30% run to run and made the cross-layer
            # fraction a coin flip.  Per-block medians still ride in
            # the detail for honesty.
            results = []
            layers = ("endpoint", "http", "http_bin")
            block_ratios = {layer: [] for layer in layers}
            for block in range(blocks):
                legs = ((serialized, batched) if block % 2 == 0
                        else (batched, serialized))
                for layer in layers:
                    wall = {}
                    for rig in legs:
                        wall[rig.label] = rig.timed_block(
                            layer, conc, requests_per_client)
                    block_ratios[layer].append(
                        wall["serialized"] / wall["batched"])
            medians = {}
            for layer in layers:
                ordered = sorted(block_ratios[layer])
                medians[layer] = ordered[len(ordered) // 2]
                results.append(serialized.result(
                    layer, conc, requests_per_client))
                results.append(batched.result(
                    layer, conc, requests_per_client))
            for r in results:
                print(json.dumps(r))

            def _best_ratio(layer):
                return (serialized.best[(layer, conc)]
                        / batched.best[(layer, conc)])

            endpoint_ratio = _best_ratio("endpoint")
            json_ratio = _best_ratio("http")
            bin_ratio = _best_ratio("http_bin")
            bin_fraction = bin_ratio / max(1e-9, endpoint_ratio)
            json_fraction = json_ratio / max(1e-9, endpoint_ratio)
            p99_json = _hist_p99_ms(
                batched.counters[("http", conc)])
            p99_bin = _hist_p99_ms(
                batched.counters[("http_bin", conc)])
            router_leg = _run_router_passthrough(batched)
            transfer = _run_frame_transfer_leg()
        finally:
            serialized.close()
            batched.close()

    gates = {
        "e2e_approaches_endpoint": bool(
            bin_fraction >= WIRE_APPROACH_FLOOR),
        "p99_within_slack": bool(
            p99_json is not None and p99_bin is not None
            and p99_bin <= p99_json * WIRE_P99_SLACK),
        "bit_identical_responses": identical,
        "router_byte_identical": bool(
            router_leg["routed_status"] == 200
            and router_leg["byte_identical_response"]),
    }
    print(json.dumps({
        "metric": "serving_binary_plane",
        "value": round(bin_fraction, 3),
        "unit": "binary e2e ratio at c=%d as a fraction of the "
                "endpoint-layer ratio (best-of-block legs; 1.0 = "
                "zero transport dilution; gate >= %.2f)"
                % (conc, WIRE_APPROACH_FLOOR),
        "vs_baseline": round(json_fraction, 3),
        "detail": {
            "all_green": all(gates.values()),
            "gates": gates,
            "endpoint_ratio": round(endpoint_ratio, 2),
            "json_e2e_ratio": round(json_ratio, 2),
            "binary_e2e_ratio": round(bin_ratio, 2),
            "median_block_ratios": {
                layer: round(value, 2)
                for layer, value in sorted(medians.items())},
            "p99_ms_json_server_side": p99_json,
            "p99_ms_binary_server_side": p99_bin,
            "router_passthrough": router_leg,
            "frame_transfer": transfer,
            "concurrency": conc,
            "baseline": "self-relative: the JSON http layer on the "
                        "same rig IS the dilution baseline; "
                        "endpoint-layer ratio is the transport-free "
                        "ceiling (PR 3 measured JSON e2e at ~51% of "
                        "it on this class of rig)",
        },
    }))
    return gates


def main(argv=None):
    import argparse

    import jax

    parser = argparse.ArgumentParser("bench_serving")
    parser.add_argument("--requests_per_client", type=int,
                        default=REQUESTS_PER_CLIENT)
    parser.add_argument("--max_batch_size", type=int, default=None,
                    help="batch cap; defaults to %d (default mode) or %d\n(--wire mode, sized for its 64-row slates)"
                         % (MAX_BATCH, WIRE_MAX_BATCH))
    parser.add_argument("--batch_timeout_ms", type=float,
                        default=TIMEOUT_MS)
    parser.add_argument("--fleet", action="store_true",
                        help="run the multi-replica fleet leg (replica "
                             "subprocesses behind the router, hot-swap "
                             "mid-storm, PS-backed lookup) instead of "
                             "the single-server batching comparison")
    parser.add_argument("--wire", action="store_true",
                        help="run the binary data-plane leg (JSON vs "
                             "binary frames at c=16, p99 gate off the "
                             "serving.request histogram, router "
                             "pass-through byte-identity, npz-vs-"
                             "frame transfer) instead of the batching "
                             "comparison")
    parser.add_argument("--blocks", type=int, default=BLOCKS)
    args = parser.parse_args(argv)

    if args.fleet:
        run_fleet_bench()
        return

    if args.wire:
        if os.environ.get("ELASTICDL_TPU_PLATFORM"):
            jax.config.update(
                "jax_platforms",
                os.environ["ELASTICDL_TPU_PLATFORM"])
        gates = run_wire_bench(args.requests_per_client,
                               args.max_batch_size,
                               args.batch_timeout_ms,
                               blocks=args.blocks)
        if not all(gates.values()):
            raise SystemExit("wire gates failed: %s" % gates)
        return

    if os.environ.get("ELASTICDL_TPU_PLATFORM"):
        jax.config.update(
            "jax_platforms", os.environ["ELASTICDL_TPU_PLATFORM"])

    from elasticdl_tpu.serving.batcher import BatchConfig

    with tempfile.TemporaryDirectory() as tmp:
        export_dir = os.path.join(tmp, "export")
        _export_mlp(export_dir)
        serialized = _Rig(export_dir, None)
        batched = _Rig(export_dir, BatchConfig(
            max_batch_size=args.max_batch_size or MAX_BATCH,
            batch_timeout_ms=args.batch_timeout_ms))
        try:
            # Numerical identity gate before any timing.
            probe = _payload(3)
            probe["instances"] = probe["instances"] * 3
            want = serialized.predict_http_once(probe)
            got = batched.predict_http_once(probe)
            identical = bool(np.array_equal(
                np.asarray(want), np.asarray(got)))
            if not identical:
                raise SystemExit(
                    "batched predictions differ from serialized")

            results = []
            for layer in ("endpoint", "http"):
                for concurrency in CONCURRENCY:
                    for _ in range(BLOCKS):  # interleaved pairs
                        serialized.timed_block(
                            layer, concurrency,
                            args.requests_per_client)
                        batched.timed_block(
                            layer, concurrency,
                            args.requests_per_client)
                    results.append(serialized.result(
                        layer, concurrency, args.requests_per_client))
                    results.append(batched.result(
                        layer, concurrency, args.requests_per_client))
            for r in results:
                print(json.dumps(r))

            by = {(r["mode"], r["layer"], r["concurrency"]): r
                  for r in results}

            def ratio(layer, conc):
                return round(
                    by[("batched", layer, conc)]["requests_per_sec"]
                    / max(1e-9, by[("serialized", layer, conc)]
                          ["requests_per_sec"]), 2)

            top = HEADLINE_CONCURRENCY
            ser = by[("serialized", "endpoint", top)]
            bat = by[("batched", "endpoint", top)]
            print(json.dumps({
                "metric": "serving_batching_throughput",
                "value": ratio("endpoint", top),
                "unit": "x predict throughput (batched vs serialized "
                        "lock, %d closed-loop clients, endpoint "
                        "layer)" % top,
                "vs_baseline": None,
                "detail": {
                    "identical_responses": identical,
                    "endpoint_speedup_by_concurrency": {
                        str(c): ratio("endpoint", c)
                        for c in CONCURRENCY},
                    "http_speedup_by_concurrency": {
                        str(c): ratio("http", c) for c in CONCURRENCY},
                    "p99_ms_serialized_endpoint": ser["p99_ms"],
                    "p99_ms_batched_endpoint": bat["p99_ms"],
                    "mean_batch_occupancy": bat[
                        "mean_batch_occupancy"],
                    "max_batch_size": args.max_batch_size
                    or MAX_BATCH,
                    "batch_timeout_ms": args.batch_timeout_ms,
                    "baseline": "self-relative: the serialized "
                                "execution-lock server IS the "
                                "baseline; reference delegates this "
                                "role to TF Serving's batcher",
                },
            }))
        finally:
            serialized.close()
            batched.close()


if __name__ == "__main__":
    main()
