import time

from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elastic_pb2 as pb


def make_tm(**kw):
    defaults = dict(
        training_shards=[("f", 0, 100)], records_per_task=30, num_epochs=1
    )
    defaults.update(kw)
    return TaskManager(**defaults)


def test_shard_splitting():
    tm = make_tm()
    sizes = []
    while True:
        t = tm.get(0)
        if t is None:
            break
        sizes.append(t.shard.size)
        tm.report(t.id, True)
    assert sizes == [30, 30, 30, 10]
    assert tm.finished()


def test_report_failure_requeues_up_to_max_retries():
    tm = make_tm(training_shards=[("f", 0, 10)], records_per_task=10,
                 max_task_retries=2)
    t = tm.get(0)
    for _ in range(2):
        result = tm.report(t.id, False, "boom")
        assert not result.ok and not result.permanent_failure
        t = tm.get(0)
        assert t is not None
    result = tm.report(t.id, False, "boom")  # exceeds retries
    assert result.permanent_failure
    assert tm.get(0) is None
    assert tm.failed_counts[pb.TRAINING] == 1
    assert tm.finished()


def test_epochs_regenerate_tasks():
    tm = make_tm(
        training_shards=[("f", 0, 20)], records_per_task=10, num_epochs=3
    )
    done = 0
    while True:
        t = tm.get(0)
        if t is None:
            break
        tm.report(t.id, True)
        done += 1
    assert done == 6  # 2 tasks x 3 epochs
    assert tm.finished()


def test_shuffle_produces_record_indices():
    tm = make_tm(
        training_shards=[("f", 0, 16)], records_per_task=8,
        shuffle=True, seed=42,
    )
    t = tm.get(0)
    assert sorted(t.shard.record_indices) == list(range(t.shard.start,
                                                        t.shard.end))


def test_recover_tasks_requeues_dead_workers_tasks():
    tm = make_tm(training_shards=[("f", 0, 40)], records_per_task=10)
    t1 = tm.get(1)
    t2 = tm.get(1)
    t3 = tm.get(2)
    tm.recover_tasks(1)
    counts = tm.counts()
    assert counts["todo"] == 3  # 1 untouched + 2 recovered
    assert counts["doing"] == 1
    tm.report(t3.id, True)
    ids = set()
    while True:
        t = tm.get(3)
        if t is None:
            break
        ids.add(t.id)
        tm.report(t.id, True)
    assert t1.id in ids and t2.id in ids


def test_timeout_watchdog_requeues_and_notifies():
    tm = make_tm(
        training_shards=[("f", 0, 10)], records_per_task=10,
        task_timeout_secs=0.01,
    )
    timed_out_workers = []
    tm.add_worker_timeout_callback(timed_out_workers.append)
    tm._watchdog_interval = 0.05
    t = tm.get(7)
    # run one watchdog sweep inline instead of waiting 5s
    time.sleep(0.05)
    tm._stopped.set()
    threshold = tm._timeout_threshold()
    assert threshold <= 0.05 or threshold == 0.01
    # simulate the sweep
    tm.report(t.id, False, "timeout")
    for fn in tm._worker_timeout_callbacks:
        fn(7)
    assert timed_out_workers == [7]
    assert tm.counts()["todo"] == 1


def test_train_end_callback_task_dispatched_once():
    tm = make_tm(training_shards=[("f", 0, 10)], records_per_task=10)
    tm.set_train_end_callback_task()
    t = tm.get(0)
    tm.report(t.id, True)
    assert not tm.finished()
    cb = tm.get(0)
    assert cb is not None and cb.type == pb.TRAIN_END_CALLBACK
    assert tm.get(1) is None  # only one callback task
    tm.report(cb.id, True)
    assert tm.finished()


def test_evaluation_tasks_interleave():
    tm = make_tm(
        training_shards=[("f", 0, 20)],
        evaluation_shards=[("e", 0, 10)],
        records_per_task=10,
    )
    n = tm.create_evaluation_tasks(model_version=5)
    assert n == 1
    types = []
    while True:
        t = tm.get(0)
        if t is None:
            break
        types.append(t.type)
        tm.report(t.id, True)
    assert types[0] == pb.EVALUATION  # eval jumps the queue
    assert types.count(pb.TRAINING) == 2
