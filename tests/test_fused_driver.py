"""Fused-step training driver (worker/fused_driver.py): multi-step
dispatch equivalence, cadence alignment, coalesced progress RPCs, and
the preemption drill (zero lost records, zero double counts)."""

from types import SimpleNamespace

import numpy as np
import pytest

from elasticdl_tpu.data.reader import ArrayDataReader
from elasticdl_tpu.models import mnist
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils.args import parse_master_args, parse_worker_args
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer
from elasticdl_tpu.worker.data_shard_service import DataShardService
from elasticdl_tpu.worker.fused_driver import LossRing
from elasticdl_tpu.worker.worker import Worker


@pytest.fixture(scope="module")
def spec():
    return mnist.model_spec(learning_rate=1e-3)


@pytest.fixture(scope="module")
def dataset():
    return mnist.synthetic_data(n=192, seed=1)


class FakeMasterClient:
    """Task queue + RPC recorder: counts every report_batch_done call
    (the coalescing assertion) and the record totals (the accounting
    assertion)."""

    def __init__(self, sizes, worker_id=0):
        self.worker_id = worker_id
        self._tasks = [
            SimpleNamespace(
                id=i + 1, type=pb.TRAINING,
                shard=SimpleNamespace(name="s", start=sum(sizes[:i]),
                                      end=sum(sizes[:i]) + size,
                                      record_indices=[]),
                model_version=-1,
            )
            for i, size in enumerate(sizes)
        ]
        self.batch_done_calls = []   # record_count per RPC
        self.task_results = []       # (task_id, err_message, requeue)
        self.versions = []           # report_version stream

    def get_task(self, task_type=None):
        if self._tasks:
            return self._tasks.pop(0)
        # id < 0 and type != WAIT: "job finished" (fetch_task -> None)
        return SimpleNamespace(id=-1, type=-1, shard=None,
                               model_version=-1)

    def report_batch_done(self, count, telemetry=None):
        self.batch_done_calls.append(count)

    def report_task_result(self, task_id, err_message="",
                           exec_counters=None, requeue=False):
        self.task_results.append((task_id, err_message, requeue))

    def report_version(self, version):
        self.versions.append(version)


def run_worker(dataset, spec, fused_steps, device_prefetch=2,
               accum_steps=1, batch_size=32, records_per_shard=64,
               trainer_kwargs=None, mc=None, worker_hook=None):
    xs, ys = dataset
    reader = ArrayDataReader((xs, ys), records_per_shard=records_per_shard)
    if mc is None:
        sizes = [records_per_shard] * (len(xs) // records_per_shard)
        mc = FakeMasterClient(sizes)
    trainer = CollectiveTrainer(
        spec, batch_size=batch_size // max(1, accum_steps),
        accum_steps=accum_steps, rng_seed=0, master_client=mc,
        **(trainer_kwargs or {}),
    )
    worker = Worker(
        mc, reader, spec, trainer, batch_size=batch_size,
        fused_steps=fused_steps, device_prefetch=device_prefetch,
    )
    if worker_hook is not None:
        worker_hook(worker, trainer)
    worker.run()
    return mc, trainer, worker


# -- equivalence ------------------------------------------------------------


@pytest.mark.parametrize("fused_steps", [2, 4])
def test_fused_matches_per_step_loop(dataset, spec, fused_steps):
    """K steps per dispatch == K per-step dispatches, same seed: loss
    trajectory and final params bit-tolerant, cadence/version counts
    identical."""
    mc_ref, ref, _ = run_worker(dataset, spec, fused_steps=1)
    mc_f, fused, _ = run_worker(dataset, spec, fused_steps=fused_steps)
    assert fused.version == ref.version
    p_ref, p_fused = ref.export_parameters(), fused.export_parameters()
    for k in p_ref:
        np.testing.assert_allclose(p_ref[k], p_fused[k], rtol=2e-4,
                                   atol=1e-6)
    # identical record accounting, fewer RPCs
    assert sum(mc_f.batch_done_calls) == sum(mc_ref.batch_done_calls)
    assert len(mc_f.batch_done_calls) < len(mc_ref.batch_done_calls)


def test_fused_steps_one_is_exact_old_path(dataset, spec):
    """--fused_steps 1 routes through the classic per-step loop: params
    BIT-identical to a default worker, one RPC per batch."""
    mc_a, a, worker_a = run_worker(dataset, spec, fused_steps=1)
    mc_b, b, _ = run_worker(dataset, spec, fused_steps=1)
    assert worker_a._windowed_driver() is None
    for k, v in a.export_parameters().items():
        np.testing.assert_array_equal(v, b.export_parameters()[k])
    assert mc_a.batch_done_calls == mc_b.batch_done_calls
    assert len(mc_a.batch_done_calls) == 192 // 32


def test_fused_with_gradient_accumulation(dataset, spec):
    """Windows compose with accum_steps > 1 (stacked [K, accum, micro]
    batches)."""
    _, ref, _ = run_worker(dataset, spec, fused_steps=1, accum_steps=2)
    _, fused, _ = run_worker(dataset, spec, fused_steps=2, accum_steps=2)
    assert fused.version == ref.version
    p_ref = ref.export_parameters()
    p_fused = fused.export_parameters()
    for k in p_ref:
        np.testing.assert_allclose(p_ref[k], p_fused[k], rtol=2e-4,
                                   atol=1e-6)


def test_device_prefetch_zero_matches(dataset, spec):
    """--device_prefetch 0 (prep on the dispatch path, no staged
    transfer) is numerically identical to the double-buffered path."""
    _, staged, _ = run_worker(dataset, spec, fused_steps=4,
                              device_prefetch=2)
    _, inline, _ = run_worker(dataset, spec, fused_steps=4,
                              device_prefetch=0)
    for k, v in staged.export_parameters().items():
        np.testing.assert_array_equal(v, inline.export_parameters()[k])


# -- cadence alignment ------------------------------------------------------


def test_report_and_checkpoint_land_on_per_step_numbers(
    dataset, spec, tmp_path
):
    """Windows clamp to the next report/checkpoint boundary: version
    reports and checkpoints fire at exactly the step numbers the
    per-step loop fires them at."""
    from elasticdl_tpu.utils.checkpoint import CheckpointSaver

    def run(fused_steps, subdir):
        saver = CheckpointSaver(str(tmp_path / subdir))
        mc, trainer, _ = run_worker(
            dataset, spec, fused_steps=fused_steps,
            trainer_kwargs=dict(
                report_version_steps=2,
                checkpoint_saver=saver, checkpoint_steps=3,
            ),
        )
        trainer.flush_checkpoints()
        return mc.versions, saver

    versions_ref, saver_ref = run(1, "ref")
    versions_fused, saver = run(4, "fused")
    assert versions_fused == versions_ref == [2, 4, 6]
    # 6 steps, cadence 3 -> checkpoints at versions 3 and 6, both paths
    assert saver.latest_version() == saver_ref.latest_version() == 6


def test_steps_to_boundary(spec):
    trainer = CollectiveTrainer(
        spec, batch_size=16, master_client=FakeMasterClient([]),
        report_version_steps=5,
    )
    assert trainer.steps_to_boundary() == 5
    xs, ys = mnist.synthetic_data(n=16, seed=2)
    trainer.train_minibatch(xs, ys)
    assert trainer.steps_to_boundary() == 4
    bare = CollectiveTrainer(spec, batch_size=16)
    assert bare.steps_to_boundary() is None


# -- coalesced progress RPCs ------------------------------------------------


def test_one_report_batch_done_rpc_per_window(dataset, spec):
    """192 records / batch 32 = 6 batches; K=2 -> 3 RPCs per... the
    windows span tasks of 2 batches each, so: one RPC per window, sum
    of counts exact."""
    mc, _, _ = run_worker(dataset, spec, fused_steps=2)
    assert sum(mc.batch_done_calls) == 192
    # 3 tasks x (one 2-batch window each) = 3 RPCs
    assert len(mc.batch_done_calls) == 3
    assert all(c == 64 for c in mc.batch_done_calls)


def test_deferred_counts_flush_on_task_boundaries():
    """DataShardService: deferred counts auto-flush when a shard drains
    (task boundary) and on report_task_failed/done — never lost, never
    doubled."""
    mc = FakeMasterClient([])
    svc = DataShardService(mc, batch_size=5)
    task = SimpleNamespace(
        id=7, type=pb.TRAINING,
        shard=SimpleNamespace(name="s", start=0, end=10,
                              record_indices=[]),
        model_version=-1,
    )
    mc._tasks = [task]
    t = svc.fetch_task()
    svc.report_batch_done(5, defer=True)
    assert mc.batch_done_calls == []          # buffered
    svc.flush_batch_done()
    assert mc.batch_done_calls == [5]         # one coalesced RPC
    svc.flush_batch_done()
    assert mc.batch_done_calls == [5]         # idempotent when empty
    svc.report_batch_done(5, defer=True)      # drains the shard ->
    assert mc.batch_done_calls == [5, 5]      # mandatory flush
    assert (t.id, "", False) in mc.task_results
    # failure path flushes too
    task2 = SimpleNamespace(
        id=8, type=pb.TRAINING,
        shard=SimpleNamespace(name="s", start=10, end=20,
                              record_indices=[]),
        model_version=-1,
    )
    mc._tasks = [task2]
    t2 = svc.fetch_task()
    svc.report_batch_done(5, defer=True)
    svc.report_task_failed(t2, "preempted", requeue=True)
    assert mc.batch_done_calls == [5, 5, 5]
    assert (t2.id, "preempted", True) in mc.task_results


# -- preemption drill -------------------------------------------------------


def test_preemption_mid_window_loses_and_double_counts_nothing(
    dataset, spec
):
    """The elastic drill: preempt during a fused task.  The in-flight
    window is flushed (counted exactly once), collected-but-undispatched
    batches are the unconsumed remainder (never counted), the task is
    requeued without consuming a retry, and a second worker finishes
    every record."""
    xs, ys = dataset
    reader = ArrayDataReader((xs, ys), records_per_shard=192)
    mc = FakeMasterClient([192])
    trainer = CollectiveTrainer(spec, batch_size=32, rng_seed=0,
                                master_client=mc)
    worker = Worker(mc, reader, spec, trainer, batch_size=32,
                    fused_steps=2)

    real_train_window = trainer.train_window
    windows = []

    def spy_train_window(staged):
        windows.append(staged.size)
        if len(windows) == 2:  # preempt DURING the second window
            worker.request_stop()
        return real_train_window(staged)

    trainer.train_window = spy_train_window
    worker.run()
    assert worker.preempted
    # exactly the two dispatched windows were counted, once each
    assert windows == [2, 2]
    assert sum(mc.batch_done_calls) == 4 * 32
    # the task went back with requeue=True (no retry consumed)
    assert mc.task_results == [(1, "worker preempted (graceful)", True)]

    # a replacement worker picks the task back up and finishes it
    mc2 = FakeMasterClient([])
    mc2._tasks = [SimpleNamespace(
        id=1, type=pb.TRAINING,
        shard=SimpleNamespace(name="s", start=0, end=192,
                              record_indices=[]),
        model_version=-1,
    )]
    worker2 = Worker(mc2, reader, spec, trainer, batch_size=32,
                     fused_steps=2)
    worker2.run()
    assert sum(mc2.batch_done_calls) == 192     # zero lost records
    assert mc2.task_results == [(1, "", False)]


def test_preemption_between_tasks_old_loop_unchanged(dataset, spec):
    """fused_steps=1 keeps the seed preemption semantics."""
    def hook(worker, trainer):
        orig = trainer.train_minibatch

        def stop_after_one(f, l):
            loss, v = orig(f, l)
            if v == 1:  # mid-task: one of the task's two batches done
                worker.request_stop()
            return loss, v

        trainer.train_minibatch = stop_after_one

    mc, _, worker = run_worker(dataset, spec, fused_steps=1,
                               worker_hook=hook)
    assert worker.preempted
    assert sum(mc.batch_done_calls) == 32
    assert mc.task_results == [(1, "worker preempted (graceful)", True)]


# -- lazy loss + loss ring --------------------------------------------------


def test_train_minibatch_returns_lazy_device_loss(spec):
    trainer = CollectiveTrainer(spec, batch_size=16)
    xs, ys = mnist.synthetic_data(n=16, seed=5)
    loss, version = trainer.train_minibatch(xs, ys)
    assert not isinstance(loss, float)       # lazy device scalar
    assert hasattr(loss, "dtype")
    assert np.isfinite(float(loss))          # explicit fetch works
    assert version == 1


def test_loss_ring_single_sync_and_clear(spec):
    trainer = CollectiveTrainer(spec, batch_size=16)
    xs, ys = mnist.synthetic_data(n=32, seed=6)
    ring = LossRing()
    assert ring.fetch_last() is None
    prepared = [trainer.prepare_batch(xs[:16], ys[:16]),
                trainer.prepare_batch(xs[16:], ys[16:])]
    losses, version = trainer.train_window(trainer.stage_window(prepared))
    ring.push(2, losses)
    step, value = ring.fetch_last()
    assert step == 2 and np.isfinite(value)
    assert len(ring) == 0 and ring.fetch_last() is None


# -- pad-plan cache ---------------------------------------------------------


def test_pad_plan_cached_per_shape(spec):
    trainer = CollectiveTrainer(spec, batch_size=16)
    xs, ys = mnist.synthetic_data(n=40, seed=7)
    trainer.prepare_batch(xs[:16], ys[:16])
    trainer.prepare_batch(xs[16:32], ys[16:32])
    assert len(trainer._pad_plans) == 1          # full batch: one plan
    partial = trainer.prepare_batch(xs[32:40], ys[32:40])
    assert len(trainer._pad_plans) == 2          # tail batch adds one
    # padded to the static batch with a correct loss mask
    leaves = np.asarray(partial.features)
    assert leaves.shape[0] == 16
    assert partial.weights.sum() == 8.0
    assert partial.count == 8


def test_pad_plan_cache_invalidated_on_rebuild(spec):
    trainer = CollectiveTrainer(spec, batch_size=16)
    xs, ys = mnist.synthetic_data(n=16, seed=8)
    trainer.prepare_batch(xs, ys)
    trainer.stage_window(
        [trainer.prepare_batch(xs, ys), trainer.prepare_batch(xs, ys)]
    )
    fn = trainer.build_fused_window(2)
    trainer._fused_window_cache[2] = fn
    trainer.rebuild(None)
    assert trainer._pad_plans == {}
    assert trainer._fused_window_cache == {}


def test_prepare_batch_accum_reshape(spec):
    trainer = CollectiveTrainer(spec, batch_size=8, accum_steps=2)
    xs, ys = mnist.synthetic_data(n=16, seed=9)
    prepared = trainer.prepare_batch(xs, ys)
    assert np.asarray(prepared.features).shape[:2] == (2, 8)
    assert prepared.weights.shape == (2, 8)


# -- timing + args ----------------------------------------------------------


def test_timing_sync_fraction():
    t = Timing()
    assert t.sync_fraction("window_dispatch", "loss_sync") is None
    t.observe("window_dispatch", 3.0)
    t.observe("loss_sync", 1.0)
    assert t.sync_fraction("window_dispatch", "loss_sync") == 0.25


def test_step_anatomy_phases_and_step_time_hist(dataset, spec):
    """The fused loop decomposes into data_wait / host_prep /
    window_dispatch / loss_sync / progress_rpc phases (each
    histogram-backed via Timing), and observes one step_time sample
    per step — the distribution the telemetry piggyback ships to the
    master (docs/observability.md)."""
    mc, _trainer, worker = run_worker(dataset, spec, fused_steps=4)
    timing = worker.timing
    step_snap = timing.hist_snapshot("step_time")
    assert step_snap is not None
    assert step_snap["count"] == worker._steps  # one sample per step
    for phase in ("data_wait", "window_dispatch", "progress_rpc"):
        snap = timing.hist_snapshot(phase)
        assert snap is not None and snap["count"] > 0, phase
    # host_prep only when staging ahead ran (device_prefetch > 0)
    assert timing.hist_snapshot("host_prep") is not None
    # and the telemetry snapshot carries the encoded delta
    worker2_out = worker._telemetry_snapshot()
    assert "hist_delta" in worker2_out


def test_fused_flags_roundtrip_master_to_worker():
    args = parse_master_args([
        "--fused_steps", "8", "--device_prefetch", "4",
    ])
    from elasticdl_tpu.master.main import _MASTER_ONLY_ARGS
    from elasticdl_tpu.utils.args import build_arguments_from_parsed_result

    flags = build_arguments_from_parsed_result(
        args, filter_args=_MASTER_ONLY_ARGS
    )
    worker_args = parse_worker_args(flags)
    assert worker_args.fused_steps == 8
    assert worker_args.device_prefetch == 4
    defaults = parse_worker_args([])
    assert defaults.fused_steps == 1       # the exact old path
    assert defaults.device_prefetch == 2


# -- PS trainer passthrough -------------------------------------------------


def test_ps_trainer_window_api_is_passthrough():
    """The PS trainer exposes the same driver API but max_window=1
    keeps it on the per-step loop; a Worker with fused_steps>1 must
    therefore NOT select the windowed driver for it."""
    from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer

    assert ParameterServerTrainer.max_window.fget(None) == 1
    trainer = ParameterServerTrainer.__new__(ParameterServerTrainer)
    assert trainer.steps_to_boundary() is None
    features = {"x": np.zeros((4, 3), np.float32)}
    labels = np.zeros((4,), np.int32)
    prepared = trainer.prepare_batch(features, labels)
    assert prepared.count == 4 and prepared.weights is None
    staged = trainer.stage_window([prepared])
    assert staged.size == 1
    assert staged.features[0] is features  # raw dict, IDS_KEY intact


class _CappedTrainer(CollectiveTrainer):
    """PS-style structural cap: window 1 regardless of --fused_steps."""

    @property
    def max_window(self):
        return 1


def test_dispatch_splits_window_when_cap_shrinks(dataset, spec):
    """An elastic epoch re-form can shrink max_window between collect
    and dispatch (world grows to multi-controller): the driver then
    dispatches the already-collected window per-step — bit-identical
    to the per-step loop, no task failure."""
    from elasticdl_tpu.worker.fused_driver import FusedStepDriver

    xs, ys = dataset
    trainer = _CappedTrainer(spec, batch_size=32, rng_seed=0)
    driver = FusedStepDriver(trainer, None, Timing(), fused_steps=2)
    cur = [trainer.prepare_batch(xs[:32], ys[:32]),
           trainer.prepare_batch(xs[32:64], ys[32:64])]
    losses, version = driver._dispatch(cur, None)
    assert version == 2 and len(losses) == 2
    ref = CollectiveTrainer(spec, batch_size=32, rng_seed=0)
    ref.train_minibatch(xs[:32], ys[:32])
    ref.train_minibatch(xs[32:64], ys[32:64])
    p = trainer.export_parameters()
    for k, v in ref.export_parameters().items():
        np.testing.assert_array_equal(v, p[k])


def test_worker_routes_ps_style_trainer_to_per_step_loop(dataset, spec):
    """A trainer whose max_window is 1 (the PS path) never enters the
    windowed driver even with --fused_steps 4."""
    xs, ys = dataset
    reader = ArrayDataReader((xs, ys), records_per_shard=64)
    mc = FakeMasterClient([64, 64, 64])
    trainer = _CappedTrainer(spec, batch_size=32, rng_seed=0,
                             master_client=mc)
    worker = Worker(mc, reader, spec, trainer, batch_size=32,
                    fused_steps=4)
    assert worker._windowed_driver() is None
    worker.run()
    assert len(mc.batch_done_calls) == 6   # per-batch RPCs (old loop)
