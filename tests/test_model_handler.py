"""Embedding placement plan + spec localization (ModelHandler analog)."""

import numpy as np

from elasticdl_tpu.models import deepfm
from elasticdl_tpu.models.model_handler import (
    localize_spec,
    plan_embedding_placement,
)
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer


def test_placement_threshold_matches_reference_2mb():
    infos = [
        {"name": "small", "dim": 8},    # 1000*8*4 = 32 KB -> device
        {"name": "big", "dim": 64},     # 10M*64*4 = 2.5 GB -> ps
        {"name": "unknown", "dim": 8},  # no vocab -> ps
    ]
    plan = plan_embedding_placement(
        infos, {"small": 1000, "big": 10_000_000}
    )
    assert plan == {"ps": ["big", "unknown"], "device": ["small"]}


def test_localized_deepfm_trains_without_ps():
    vocab = 500
    spec = deepfm.model_spec(vocab_size=vocab, embedding_dim=4,
                             hidden=(16,))
    local = localize_spec(
        spec,
        {deepfm.EMB_TABLE: vocab, deepfm.LIN_TABLE: vocab},
    )
    assert local.ps_embedding_infos == []  # everything on device
    trainer = CollectiveTrainer(local, batch_size=32)
    dense, ids, labels = deepfm.synthetic_data(n=64, vocab_size=vocab)
    records = [(dense[i], ids[i], labels[i]) for i in range(64)]
    feats, ys = local.feed(records[:32])
    assert "__ids__" not in feats
    loss1, _ = trainer.train_minibatch(feats, ys)
    for _ in range(15):
        loss2, _ = trainer.train_minibatch(feats, ys)
    assert np.isfinite(loss2) and loss2 < loss1


def test_hybrid_localization_keeps_big_tables_on_ps():
    spec = deepfm.model_spec(vocab_size=500, embedding_dim=4)
    hybrid = localize_spec(
        spec, {deepfm.LIN_TABLE: 500}, tables=[deepfm.LIN_TABLE]
    )
    names = [i["name"] for i in hybrid.ps_embedding_infos]
    assert names == [deepfm.EMB_TABLE]
    dense, ids, labels = deepfm.synthetic_data(n=8, vocab_size=500)
    feats, _ = hybrid.feed([(dense[i], ids[i], labels[i])
                            for i in range(8)])
    assert deepfm.LIN_TABLE not in feats.get("__ids__", {})
    assert deepfm.EMB_TABLE in feats["__ids__"]
