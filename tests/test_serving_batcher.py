"""Dynamic request batching on the serving hot path (serving/batcher.py):
coalescing, bucketed padding, hot-swap version discipline, timeout
flushes, :lookup through the admission queue, /statz counters, and the
batching-off escape hatch preserving the serialized path exactly."""

import json
import os
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.serving.batcher import (
    BatchConfig,
    batch_plan,
    default_buckets,
    pick_bucket,
)
from elasticdl_tpu.serving.export import export_servable
from elasticdl_tpu.serving.server import ModelEndpoint, build_server

W = np.arange(8, dtype=np.float32).reshape(4, 2)


def _linear_export(path, model_name="lin"):
    export_servable(
        str(path), lambda p, x: x @ p["w"], {"w": W},
        np.zeros((1, 4), np.float32), model_name=model_name,
        embeddings={"users": (np.array([5, 9]),
                              np.arange(8, dtype=np.float32)
                              .reshape(2, 4))},
        platforms=("cpu",),
    )


def _config(**kw):
    kw.setdefault("max_batch_size", 8)
    kw.setdefault("batch_timeout_ms", 300.0)
    kw.setdefault("warm", False)
    return BatchConfig(**kw)


def test_default_buckets_and_pick():
    assert default_buckets(1) == [1]
    assert default_buckets(8) == [1, 2, 4, 8]
    assert default_buckets(12) == [1, 2, 4, 8, 12]
    assert pick_bucket(3, [1, 2, 4, 8]) == 4
    assert pick_bucket(8, [1, 2, 4, 8]) == 8
    with pytest.raises(ValueError):
        default_buckets(0)


def test_batch_config_validation():
    with pytest.raises(ValueError):
        BatchConfig(max_batch_size=0)
    with pytest.raises(ValueError):
        BatchConfig(batch_timeout_ms=-1)
    with pytest.raises(ValueError):
        BatchConfig(pad_buckets=[0, 4])
    # Explicit buckets that don't cover max_batch_size get it appended:
    # a full coalesced batch must always fit the top bucket.
    cfg = BatchConfig(max_batch_size=10, pad_buckets=[2, 4])
    assert cfg.pad_buckets == [2, 4, 10]
    assert not BatchConfig(max_batch_size=1).enabled
    assert BatchConfig(max_batch_size=2).enabled


def test_batch_plan_modes(tmp_path):
    _linear_export(tmp_path / "e")
    from elasticdl_tpu.serving.loader import load_servable

    manifest = load_servable(str(tmp_path / "e")).manifest
    assert batch_plan(manifest) == {"mode": "array"}
    assert batch_plan(dict(manifest, polymorphic_batch=False)) is None
    # Dict model with a scalar aux leaf: only the free-lead leaves batch.
    plan = batch_plan({
        "polymorphic_batch": True,
        "input_signature": {
            "v": {"shape": [None, 4], "dtype": "float32"},
            "temp": {"shape": [], "dtype": "float32"},
        },
    })
    assert plan == {"mode": "dict", "batched": frozenset({"v"})}


def test_batched_responses_bit_identical_to_unbatched(tmp_path):
    """The acceptance bar: responses through the batcher (coalesced,
    padded, sliced) must equal the serialized-lock path bit for bit."""
    _linear_export(tmp_path / "e")
    plain = ModelEndpoint(str(tmp_path / "e"))
    batched = ModelEndpoint(str(tmp_path / "e"), batching=_config())
    try:
        bodies = [{"instances": [[k, k + 1, -k, 2.5 * k]
                                 for _ in range(1 + k % 3)]}
                  for k in range(8)]
        want = [plain.predict(b)["predictions"] for b in bodies]
        got = [None] * len(bodies)

        def hit(k):
            got[k] = batched.predict(bodies[k])["predictions"]

        threads = [threading.Thread(target=hit, args=(k,))
                   for k in range(len(bodies))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for k in range(len(bodies)):
            assert got[k] is not None, k
            np.testing.assert_array_equal(got[k], want[k])
        counters = batched.timing.counters()
        # 8 concurrent requests against a 300 ms window must coalesce.
        assert counters["batcher.batches"] < counters["batcher.requests"]
    finally:
        plain.close()
        batched.close()


def test_padding_rows_never_leak(tmp_path):
    """A 3-row request pads to the 4-bucket; the response must carry
    exactly 3 rows with exact values — padded rows sliced away."""
    _linear_export(tmp_path / "e")
    endpoint = ModelEndpoint(
        str(tmp_path / "e"),
        batching=_config(batch_timeout_ms=5.0))
    try:
        x = [[1, 1, 1, 1], [0, 1, 0, 0], [2, 0, 0, 1]]
        out = endpoint.predict({"instances": x})["predictions"]
        assert len(out) == 3
        np.testing.assert_array_equal(
            out, (np.asarray(x, np.float32) @ W).tolist())
        counters = endpoint.timing.counters()
        assert counters["batcher.padded_rows"] >= 1
        assert counters["batcher.rows"] == 3
    finally:
        endpoint.close()


def test_pressure_aware_flush_and_timeout_bound(tmp_path):
    """An isolated request on an idle server flushes immediately — no
    batching latency tax at concurrency 1.  Under companion pressure
    the executor block-waits for the batch window, and a lone request
    then waits at most ~batch_timeout_ms before its batch flushes."""
    _linear_export(tmp_path / "e")
    endpoint = ModelEndpoint(
        str(tmp_path / "e"),
        batching=_config(batch_timeout_ms=150.0))
    try:
        endpoint.predict({"instances": [[0, 0, 0, 0]]})  # warm compile
        t0 = time.monotonic()
        endpoint.predict({"instances": [[1, 1, 1, 1]]})
        fast = time.monotonic() - t0
        assert fast < 0.1, "idle lone request paid the batch window"
        assert endpoint.timing.counters()[
            "batcher.empty_flushes"] >= 2
        # Flag companion pressure the way a concurrent burst would.
        endpoint._batcher._had_company = True
        t0 = time.monotonic()
        out = endpoint.predict({"instances": [[1, 1, 1, 1]]})
        elapsed = time.monotonic() - t0
        np.testing.assert_array_equal(out["predictions"],
                                      [[12.0, 16.0]])
        assert elapsed >= 0.1, "pressured request skipped the window"
        assert elapsed < 2.0, "lone request stuck: %.2fs" % elapsed
        assert endpoint.timing.counters()[
            "batcher.timeout_flushes"] >= 1
    finally:
        endpoint.close()


def test_hot_swap_never_mixes_versions(tmp_path):
    """Hammer the batcher while new versions export: every response is
    internally consistent with exactly ONE exported version (a batch
    never mixes weights), and the latest version is eventually served
    — reloads take effect on the executor, between batches."""
    base = str(tmp_path / "m")
    scales = {v: float(v) for v in range(1, 5)}

    def put(version):
        export_servable(
            os.path.join(base, str(version)),
            lambda p, x: x * p["s"],
            {"s": np.float32(scales[version])},
            np.zeros((1, 2), np.float32),
            model_name="hot", version=version, platforms=("cpu",))

    put(1)
    endpoint = ModelEndpoint(
        base, poll_interval=0.01,
        batching=_config(batch_timeout_ms=10.0))
    stop = threading.Event()
    failures, seen = [], set()

    def hammer():
        while not stop.is_set():
            try:
                out = endpoint.predict(
                    {"instances": [[1.0, 1.0]]})["predictions"]
                scale = out[0][0]
                if out != [[scale, scale]] or (
                        scale not in scales.values()):
                    failures.append(out)
                seen.add(scale)
            except Exception as e:  # noqa: BLE001
                failures.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        for version in range(2, 5):
            put(version)
            time.sleep(0.3)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and 4.0 not in seen:
            time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        endpoint.close()
    assert not failures, failures[:5]
    assert 4.0 in seen  # the last version took effect
    assert len(seen) >= 2  # at least one live flip observed


def test_aux_leaf_requests_do_not_coalesce(tmp_path):
    """Dict model with a scalar aux input: requests whose aux leaves
    differ must land in different batches (the aux value is shared by
    the whole executed batch), and both must come back correct."""
    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x["v"] @ p["w"] * x["temp"],
        {"w": W},
        {"v": np.zeros((1, 4), np.float32), "temp": np.float32(1.0)},
        model_name="aux", platforms=("cpu",),
    )
    endpoint = ModelEndpoint(
        str(tmp_path / "e"),
        batching=_config(batch_timeout_ms=100.0))
    try:
        results = {}

        def hit(temp):
            results[temp] = endpoint.predict({
                "inputs": {"v": [[1, 1, 1, 1]], "temp": temp},
            })["predictions"]

        threads = [threading.Thread(target=hit, args=(temp,))
                   for temp in (2.0, 3.0)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        np.testing.assert_array_equal(results[2.0], [[24.0, 32.0]])
        np.testing.assert_array_equal(results[3.0], [[36.0, 48.0]])
        counters = endpoint.timing.counters()
        assert counters["batcher.batches"] == 2  # never coalesced
    finally:
        endpoint.close()


def test_fixed_aux_output_not_sliced_on_bucket_collision(tmp_path):
    """An output leaf whose FIXED leading dim equals the pad bucket
    must still be shared whole, not sliced per request — the export's
    output_signature, not a shape coincidence, decides what batches."""
    aux = np.arange(8, dtype=np.float32).reshape(4, 2)
    export_servable(
        str(tmp_path / "e"),
        lambda p, x: {"y": x @ p["w"], "aux": p["c"]},
        {"w": W, "c": aux},
        np.zeros((1, 4), np.float32),
        model_name="auxout", platforms=("cpu",),
    )
    plain = ModelEndpoint(str(tmp_path / "e"))
    batched = ModelEndpoint(
        str(tmp_path / "e"),
        batching=_config(batch_timeout_ms=5.0))
    try:
        sig = plain.model.manifest["output_signature"]
        assert sig["y"]["shape"] == [None, 2]
        assert sig["aux"]["shape"] == [4, 2]
        # 3 rows pad to bucket 4 == aux's fixed leading dim.
        body = {"instances": [[1, 1, 1, 1], [0, 1, 0, 0], [2, 0, 0, 1]]}
        want = plain.predict(body)["predictions"]
        got = batched.predict(body)["predictions"]
        np.testing.assert_array_equal(got["aux"], aux.tolist())
        assert got == want
    finally:
        plain.close()
        batched.close()


def test_padded_rows_counted_once_for_multi_leaf_inputs(tmp_path):
    """Dict model with two batched leaves: padding is a per-BATCH
    statistic, not per-leaf (a 3-row request padded to bucket 4 counts
    1 padded row, not 2)."""
    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x["a"] @ p["w"] + x["b"],
        {"w": W},
        {"a": np.zeros((1, 4), np.float32),
         "b": np.zeros((1, 2), np.float32)},
        model_name="two", platforms=("cpu",),
    )
    endpoint = ModelEndpoint(
        str(tmp_path / "e"),
        batching=_config(batch_timeout_ms=5.0))
    try:
        out = endpoint.predict({"inputs": {
            "a": [[1, 1, 1, 1]] * 3, "b": [[1, 2]] * 3,
        }})["predictions"]
        np.testing.assert_array_equal(out, [[13.0, 18.0]] * 3)
        assert endpoint.timing.counters()["batcher.padded_rows"] == 1
    finally:
        endpoint.close()


def test_unbatchable_model_rides_raw_path(tmp_path):
    """A fixed-shape export with batching enabled still serves: every
    predict runs on the executor (one execution point, swap-safe) but
    is never coalesced or padded."""
    export_servable(
        str(tmp_path / "e"), lambda p, x: x * p["s"],
        {"s": np.float32(2.0)}, np.zeros((1, 4), np.float32),
        model_name="fixed", polymorphic_batch=False,
        platforms=("cpu",),
    )
    endpoint = ModelEndpoint(str(tmp_path / "e"), batching=_config())
    try:
        assert endpoint._snapshot()[2] is None  # no batch plan
        out = endpoint.predict({"instances": [[1, 2, 3, 4]]})
        np.testing.assert_array_equal(out["predictions"],
                                      [[2.0, 4.0, 6.0, 8.0]])
        counters = endpoint.timing.counters()
        assert counters["batcher.raw_requests"] == 1
        # Raw batches-of-one must not drag mean_batch_occupancy down.
        assert "batcher.batches" not in counters
    finally:
        endpoint.close()


def test_lookup_rides_the_admission_queue(tmp_path):
    """:lookup ships through the same queue (concatenated ids, split
    vectors): concurrent lookups stay correct and are counted apart
    from predict batches."""
    _linear_export(tmp_path / "e")
    endpoint = ModelEndpoint(str(tmp_path / "e"), batching=_config())
    try:
        results = {}

        def hit(k, ids):
            results[k] = endpoint.lookup(
                {"table": "users", "ids": ids})["vectors"]

        specs = {0: [9, 7], 1: [5], 2: [5, 9, 5]}
        threads = [threading.Thread(target=hit, args=(k, ids))
                   for k, ids in specs.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        np.testing.assert_array_equal(
            results[0], [[4, 5, 6, 7], [0, 0, 0, 0]])
        np.testing.assert_array_equal(results[1], [[0, 1, 2, 3]])
        np.testing.assert_array_equal(
            results[2], [[0, 1, 2, 3], [4, 5, 6, 7], [0, 1, 2, 3]])
        counters = endpoint.timing.counters()
        assert counters["batcher.lookup_rows"] == 6
        assert "batcher.batches" not in counters  # no predicts ran
        with pytest.raises(KeyError):
            endpoint.lookup({"table": "nope", "ids": [1]})
    finally:
        endpoint.close()


def test_statz_and_keepalive_over_http(tmp_path):
    """/statz surfaces the batching counters per model, and the server
    speaks HTTP/1.1 keep-alive: one connection serves many requests."""
    import http.client

    _linear_export(tmp_path / "e")
    endpoint = ModelEndpoint(
        str(tmp_path / "e"),
        batching=BatchConfig(max_batch_size=4, batch_timeout_ms=5.0,
                             warm=True))
    server = build_server(endpoint, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    try:
        for k in range(3):  # sequential requests, ONE connection
            conn.request(
                "POST", "/v1/models/lin:predict",
                body=json.dumps({"instances": [[k, 0, 0, 0]]}),
            )
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.headers.get("Connection", "") != "close"
            out = json.loads(resp.read())["predictions"]
            np.testing.assert_array_equal(out, [[0.0, 1.0 * k]])
        conn.request("GET", "/statz")
        statz = json.loads(conn.getresponse().read())
        assert statz["draining"] is False  # fleet drain flag rides
        # /statz so the router's health probe keys off one payload
        stats = statz["models"]["lin"]
        assert stats["batching"]["max_batch_size"] == 4
        assert stats["batching"]["pad_buckets"] == [1, 2, 4]
        assert stats["counters"]["batcher.requests"] == 3
        assert stats["counters"]["batcher.rows"] == 3
        assert stats["counters"]["batcher.warmed_models"] == 1
        assert stats["mean_batch_occupancy"] == 1.0
        assert "batcher.queue_wait" in stats["timing"]
        assert "batcher.execute" in stats["timing"]
        # Keep-alive framing depends on Content-Length: a chunked body
        # must get 411 + close, not desync the persistent connection.
        import socket

        raw = socket.create_connection(("127.0.0.1", port),
                                       timeout=30)
        try:
            raw.sendall(b"POST /v1/models/lin:predict HTTP/1.1\r\n"
                        b"Host: t\r\nTransfer-Encoding: chunked\r\n"
                        b"\r\n")
            status = raw.recv(65536).split(b"\r\n", 1)[0]
            assert b"411" in status, status
        finally:
            raw.close()
    finally:
        conn.close()
        server.shutdown()
        server.server_close()
        endpoint.close()


def test_batching_off_preserves_serialized_path(tmp_path):
    """No batching config (or a disabled one): no executor thread, no
    queue — predict/lookup take the original execution-lock path, and
    /statz still answers with batching: null."""
    _linear_export(tmp_path / "e")
    plain = ModelEndpoint(str(tmp_path / "e"))
    disabled = ModelEndpoint(str(tmp_path / "e"),
                             batching=BatchConfig(max_batch_size=1))
    try:
        for endpoint in (plain, disabled):
            assert endpoint._batcher is None
            out = endpoint.predict({"instances": [[1, 1, 1, 1]]})
            np.testing.assert_array_equal(out["predictions"],
                                          [[12.0, 16.0]])
            assert endpoint.stats()["batching"] is None
            assert "batcher.batches" not in endpoint.timing.counters()
            endpoint.close()  # no-op without a batcher
    finally:
        plain.close()
        disabled.close()


def test_batch_config_from_cli_args():
    from elasticdl_tpu.serving.server import batch_config_from_args
    from elasticdl_tpu.utils.args import build_serving_parser

    parser = build_serving_parser()
    args = parser.parse_args(["--export_dir", "/x"])
    cfg = batch_config_from_args(args)
    assert cfg is not None and cfg.max_batch_size == 32
    assert cfg.pad_buckets == [1, 2, 4, 8, 16, 32]

    args = parser.parse_args(
        ["--export_dir", "/x", "--max_batch_size", "1"])
    assert batch_config_from_args(args) is None
    args = parser.parse_args(
        ["--export_dir", "/x", "--enable_batching", "false"])
    assert batch_config_from_args(args) is None
    args = parser.parse_args(
        ["--export_dir", "/x", "--max_batch_size", "16",
         "--pad_buckets", "4,16", "--batch_timeout_ms", "7.5",
         "--warm_buckets", "false"])
    cfg = batch_config_from_args(args)
    assert cfg.pad_buckets == [4, 16]
    assert cfg.batch_timeout_ms == 7.5
    assert cfg.warm is False
