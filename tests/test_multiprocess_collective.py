"""Genuine multi-process collective world (VERDICT r1 #7).

Two worker *processes* join the master rendezvous, receive ranks, run
``jax.distributed.initialize`` against the epoch's coordinator
(parallel/distributed.py), and execute a real cross-process collective.
Round 1 only ever exercised this path inside one process; this proves
the epoch -> initialize -> collective chain across process boundaries —
the reference's equivalent is allreduce_trainer_test.py:40-60 (real
local Horovod).

Set ELASTICDL_SKIP_MULTIPROC=1 to skip (the drill takes ~30 s).
"""

import os
import subprocess
import sys

import pytest

from elasticdl_tpu.master.master import Master
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.utils.grpc_utils import find_free_port

_WORKER_PROG = r"""
import os, sys, time

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from elasticdl_tpu.parallel.distributed import initialize_from_rendezvous
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.proto import elastic_pb2 as pb

worker_id = int(sys.argv[1])
ch = grpc_utils.build_channel(os.environ["MASTER_ADDR"])
grpc_utils.wait_for_channel_ready(ch)
mc = MasterClient(ch, worker_id=worker_id)
mc.report_train_loop_status(pb.LOOP_START)  # join the rendezvous

deadline = time.time() + 60
while time.time() < deadline:
    res = mc.get_comm_rank()
    if res.rank_id >= 0 and res.world_size == 2:
        break
    time.sleep(0.5)
else:
    raise SystemExit("rendezvous never committed a 2-worker world")

ok = initialize_from_rendezvous(
    res.rank_id, res.world_size, res.coordinator_addr
)
assert ok, "initialize_from_rendezvous declined"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()

# A real cross-process collective: allgather each process's rank.
from jax.experimental import multihost_utils

gathered = multihost_utils.process_allgather(
    np.array([res.rank_id], np.int32)
)
assert sorted(np.asarray(gathered).ravel().tolist()) == [0, 1], gathered
print("COLLECTIVE_OK rank=%d" % res.rank_id, flush=True)
"""


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("ELASTICDL_SKIP_MULTIPROC") == "1",
    reason="multi-process drill disabled",
)
def test_two_process_world_runs_collective(tmp_path):
    rendezvous = RendezvousServer(grace_secs=0.5)
    rendezvous.set_coordinator_addr(
        "localhost:%d" % find_free_port()
    )
    task_manager = TaskManager(training_shards=[("x", 0, 8)],
                               records_per_task=8)
    master = Master(task_manager, rendezvous_server=rendezvous)
    master.prepare()
    procs = []
    try:
        for wid in range(2):
            env = dict(os.environ)
            env["MASTER_ADDR"] = "localhost:%d" % master.port
            env["WORKER_ID"] = str(wid)
            # one CPU device per process -> a 2-device global world
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER_PROG, str(wid)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
            ))
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=120)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0, "worker failed:\n%s\n%s" % (out, err)
            assert "COLLECTIVE_OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        master.stop()
