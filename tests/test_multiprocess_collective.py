"""Genuine multi-process collective world (VERDICT r1 #7).

Two worker *processes* join the master rendezvous, receive ranks, run
``jax.distributed.initialize`` against the epoch's coordinator
(parallel/distributed.py), and execute a real cross-process collective.
Round 1 only ever exercised this path inside one process; this proves
the epoch -> initialize -> collective chain across process boundaries —
the reference's equivalent is allreduce_trainer_test.py:40-60 (real
local Horovod).

Set ELASTICDL_SKIP_MULTIPROC=1 to skip (the drill takes ~30 s).
"""

import os
import subprocess
import sys
import time

import pytest

from elasticdl_tpu.master.master import Master
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.utils.grpc_utils import find_free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # tools/ is repo tooling, not installed
    sys.path.insert(0, REPO)

from tools.elastic_lint.runtime_tracer import (  # noqa: E402
    LockDisciplineTracer,
)

_WORKER_PROG = r"""
import os, sys, time

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from elasticdl_tpu.parallel.distributed import initialize_from_rendezvous
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.proto import elastic_pb2 as pb

worker_id = int(sys.argv[1])
ch = grpc_utils.build_channel(os.environ["MASTER_ADDR"])
grpc_utils.wait_for_channel_ready(ch)
mc = MasterClient(ch, worker_id=worker_id)
mc.report_train_loop_status(pb.LOOP_START)  # join the rendezvous

deadline = time.time() + 60
while time.time() < deadline:
    res = mc.get_comm_rank()
    if res.rank_id >= 0 and res.world_size == 2:
        break
    time.sleep(0.5)
else:
    raise SystemExit("rendezvous never committed a 2-worker world")

ok = initialize_from_rendezvous(
    res.rank_id, res.world_size, res.coordinator_addr
)
assert ok, "initialize_from_rendezvous declined"
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 2, jax.device_count()

# A real cross-process collective: allgather each process's rank.
from jax.experimental import multihost_utils

gathered = multihost_utils.process_allgather(
    np.array([res.rank_id], np.int32)
)
assert sorted(np.asarray(gathered).ravel().tolist()) == [0, 1], gathered
print("COLLECTIVE_OK rank=%d" % res.rank_id, flush=True)
"""


_CHURN_PROG = r"""
import json, os, sys, time

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from elasticdl_tpu.api.controller import ElasticCollectiveController
from elasticdl_tpu.parallel.distributed import initialize_from_rendezvous
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.worker.master_client import MasterClient

worker_id = int(os.environ["WORKER_ID"])
deadline = time.time() + float(os.environ.get("CHURN_SECS", "30"))

ch = grpc_utils.build_channel(os.environ["MASTER_ADDR"])
grpc_utils.wait_for_channel_ready(ch)
mc = MasterClient(ch, worker_id=worker_id)


class ScalarTrainer:
    # Collective SGD on one scalar: grad(0.5*w^2) = w on every rank, so
    # with synced state the trajectory is exactly w <- 0.9*w.
    def __init__(self):
        self.w = 4.0
        self.world = 0

    def rebuild(self, world):
        self.world = world
        if world > 1:
            # Epoch-start state sync — the Horovod broadcast_parameters
            # analog: everyone adopts rank 0's weights.
            from jax.experimental import multihost_utils

            g = multihost_utils.process_allgather(
                np.array([self.w], np.float32))
            self.w = float(np.asarray(g).ravel()[0])


trainer = ScalarTrainer()
controller = ElasticCollectiveController(
    mc, trainer, check_steps=3, epoch_wait_secs=30,
    mesh_builder=lambda r, w, c: (
        initialize_from_rendezvous(r, w, c), w)[1],
)

from jax.experimental import multihost_utils

events = []


@controller.elastic_run
def train_step(step):
    g = multihost_utils.process_allgather(
        np.array([trainer.w], np.float32))
    grad = float(np.mean(np.asarray(g)))
    trainer.w -= 0.1 * grad
    events.append({"step": step, "world": trainer.world,
                   "w": round(trainer.w, 6)})


kill_self = os.environ.get("CHURN_KILL_SELF") == str(worker_id)
step = 0
with controller.scope():
    while time.time() < deadline:
        train_step(step)
        if kill_self and step == 3:
            os.kill(os.getpid(), 9)  # SIGKILL mid-run, no cleanup
        step += 1
        time.sleep(0.1)

print("CHURN-DONE " + json.dumps(
    {"worker": worker_id, "events": events}), flush=True)
"""


class _ChurnBackend:
    """WorkerManager backend launching the churn program as real
    processes (1 virtual CPU device each)."""

    def __init__(self, kill_self_id):
        self._kill_self_id = kill_self_id
        self.procs = {}

    def launch(self, worker_id, master_addr, slot=None, extra_env=None):
        env = dict(os.environ)
        env.update(extra_env or {})
        env["MASTER_ADDR"] = master_addr
        env["WORKER_ID"] = str(worker_id)
        env["JAX_PLATFORMS"] = "cpu"
        env["ELASTICDL_TPU_PLATFORM"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["ELASTICDL_COLLECTIVE_HEARTBEAT"] = "5"
        # Generous: a replacement needs ~10 s to boot + join (double
        # that on a loaded CI box), and BOTH survivors must still be
        # training when the 3-world re-forms.
        env["CHURN_SECS"] = "55"
        env["CHURN_KILL_SELF"] = str(self._kill_self_id)
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHURN_PROG],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        )
        self.procs[worker_id] = proc
        return proc

    def wait(self, ref):
        return ref.wait()

    def kill(self, ref, force=False):
        try:
            ref.kill() if force else ref.terminate()
        except ProcessLookupError:
            pass

    def is_alive(self, ref):
        return ref.poll() is None


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("ELASTICDL_SKIP_MULTIPROC") == "1",
    reason="multi-process drill disabled",
)
def test_worker_churn_mid_collective_reforms_world():
    """The reference's in-band Horovod-failure recovery, for real
    (VERDICT r4 #4, allreduce_trainer.py:77-91): a managed 3-process
    job runs REAL cross-process collectives; one worker SIGKILLs
    itself mid-run; the survivors' next collective fails in-band, the
    master notices the death and commits a smaller epoch with a FRESH
    master-hosted coordination service, the survivors re-form the
    2-world and keep training, then grow back to 3 when the relaunched
    replacement joins.  Scalar SGD makes the trajectory checkable:
    each survivor's w must decrease monotonically across the churn."""
    import json

    from elasticdl_tpu.parallel.distributed import (
        MasterCoordinationService,
    )

    coord = MasterCoordinationService()
    rendezvous = RendezvousServer(
        grace_secs=0.7, coordinator_factory=coord.start_epoch)
    task_manager = TaskManager(training_shards=[("x", 0, 8)],
                               records_per_task=8)
    backend = _ChurnBackend(kill_self_id=2)
    from elasticdl_tpu.master.worker_manager import WorkerManager

    manager = WorkerManager(backend, num_workers=3)
    master = Master(task_manager, rendezvous_server=rendezvous,
                    worker_manager=manager)
    # Dynamic EL001 over the REAL churn: the master-side epoch state is
    # hammered by gRPC pool threads (join/leave/rank RPCs), the worker
    # watcher threads, and this test thread — every access must hold
    # the respective lock (tools/elastic_lint/runtime_tracer.py).
    tracer = LockDisciplineTracer()
    tracer.register(rendezvous, attrs=[
        "_cur_hosts", "_next_hosts", "_rendezvous_id", "_last_change",
        "_coordinator_addr",
    ])
    tracer.register(task_manager, attrs=["_todo", "_doing"])
    try:
        master.prepare()
        deadline = time.time() + 120
        while time.time() < deadline:
            procs = dict(backend.procs)
            if len(procs) >= 4 and all(
                p.poll() is not None for p in procs.values()
            ):
                break
            time.sleep(1.0)
        results = {}
        for wid, proc in backend.procs.items():
            out, err = proc.communicate(timeout=30)
            for line in out.splitlines():
                if line.startswith("CHURN-DONE "):
                    results[wid] = json.loads(line[len("CHURN-DONE "):])
            if wid != 2 and wid not in results:
                raise AssertionError(
                    "worker %d produced no result:\n%s\n%s"
                    % (wid, out[-2000:], err[-3000:]))

        # The killed worker never reports; its replacement (id 3) does.
        assert 2 not in results
        assert set(results) == {0, 1, 3}
        for wid in (0, 1):
            events = results[wid]["events"]
            worlds = [e["world"] for e in events]
            # Survivors saw the full cycle: 3-world, the shrink to 2
            # after the in-band failure, and the regrowth to 3.
            assert 3 in worlds, worlds
            assert 2 in worlds, worlds
            assert worlds[-1] == 3, worlds
            assert len(events) >= 10, len(events)
            ws = [e["w"] for e in events]
            # Strictly decreasing until rounding territory (w decays
            # geometrically toward 0 and events carry 6 decimals),
            # never increasing anywhere — including across both world
            # changes.
            big = [w for w in ws if w > 1e-4]
            assert all(b < a for a, b in zip(big, big[1:])), big
            assert all(b <= a for a, b in zip(ws, ws[1:])), ws
        # The replacement joined a 3-world and synced to rank 0's w
        # (not its fresh init of 4.0) before training.
        repl = results[3]["events"]
        assert repl and repl[0]["world"] == 3, repl[:3]
        assert repl[0]["w"] < 3.6, repl[0]
        tracer.assert_clean()
    finally:
        tracer.restore()
        master.stop()
        for proc in backend.procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.communicate()


@pytest.mark.slow
@pytest.mark.skipif(
    os.environ.get("ELASTICDL_SKIP_MULTIPROC") == "1",
    reason="multi-process drill disabled",
)
def test_two_process_world_runs_collective(tmp_path):
    rendezvous = RendezvousServer(grace_secs=0.5)
    rendezvous.set_coordinator_addr(
        "localhost:%d" % find_free_port()
    )
    task_manager = TaskManager(training_shards=[("x", 0, 8)],
                               records_per_task=8)
    master = Master(task_manager, rendezvous_server=rendezvous)
    master.prepare()
    procs = []
    try:
        for wid in range(2):
            env = dict(os.environ)
            env["MASTER_ADDR"] = "localhost:%d" % master.port
            env["WORKER_ID"] = str(wid)
            # one CPU device per process -> a 2-device global world
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER_PROG, str(wid)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
            ))
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=120)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0, "worker failed:\n%s\n%s" % (out, err)
            assert "COLLECTIVE_OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        master.stop()
