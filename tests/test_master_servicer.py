"""Master gRPC service over a real in-process server."""

import time

import numpy as np

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import metrics
from tests.test_utils import create_master, create_master_client


def test_task_dispatch_and_report():
    master = create_master(
        training_shards=[("f", 0, 64)], records_per_task=32
    )
    try:
        mc = create_master_client(master)
        t1 = mc.get_task()
        assert t1.id > 0 and t1.type == pb.TRAINING
        t2 = mc.get_task()
        # queue drained: worker gets a WAIT task while t1/t2 are in doing
        t3 = mc.get_task()
        assert t3.id == -1 and t3.type == pb.WAIT
        mc.report_task_result(t1.id)
        mc.report_task_result(t2.id)
        t4 = mc.get_task()
        assert t4.id == -1 and t4.type != pb.WAIT  # job finished
    finally:
        master.stop()


def test_comm_rank_and_rendezvous_epochs():
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8, rendezvous=True
    )
    try:
        mc0 = create_master_client(master, worker_id=0)
        mc1 = create_master_client(master, worker_id=1)
        mc0.report_train_loop_status(pb.LOOP_START)
        mc1.report_train_loop_status(pb.LOOP_START)
        time.sleep(0.15)  # grace window
        r0 = mc0.get_comm_rank()
        r1 = mc1.get_comm_rank()
        assert {r0.rank_id, r1.rank_id} == {0, 1}
        assert r0.world_size == 2
        first_id = r0.rendezvous_id
        # worker 1 leaves -> epoch bumps, world shrinks
        mc1.report_train_loop_status(pb.LOOP_END)
        time.sleep(0.15)
        r0b = mc0.get_comm_rank()
        assert r0b.world_size == 1
        assert r0b.rendezvous_id > first_id
    finally:
        master.stop()


def test_evaluation_flow_end_to_end():
    master = create_master(
        training_shards=[("f", 0, 32)],
        evaluation_shards=[("e", 0, 8)],
        records_per_task=8,
        evaluation_steps=10,
        metrics_factory=lambda: {"accuracy": metrics.Accuracy()},
    )
    try:
        mc = create_master_client(master)
        # version report triggers an eval job
        mc.report_version(10)
        t = mc.get_task()
        assert t.type == pb.EVALUATION
        outputs = np.array([[0.9, 0.1], [0.2, 0.8]], np.float32)
        labels = np.array([0, 0], np.int32)
        mc.report_evaluation_metrics(outputs, labels)
        mc.report_task_result(t.id)
        history = master.evaluation_service.history
        assert history and history[0][0] == 10
        assert abs(history[0][1]["accuracy"] - 0.5) < 1e-6
    finally:
        master.stop()


def test_batch_done_counters():
    master = create_master(training_shards=[("f", 0, 8)], records_per_task=8)
    try:
        mc = create_master_client(master, worker_id=3)
        mc.report_batch_done(5)
        mc.report_batch_done(3)
        assert master.servicer.worker_record_counts[3] == 8
    finally:
        master.stop()
