"""Binary frame codec (utils/tensor_codec, docs/serving.md "Wire
protocol"): bit-exact round-trips across dtypes, zero-copy receive
views, odd/zero-length shapes, header-only stream reads, and LOUD
refusal of truncated/garbage frames — a malformed frame must raise
immediately, never hang a reader."""

import io
import json
import struct

import numpy as np
import pytest

from elasticdl_tpu.utils import tensor_codec as tc


def _rt(tensors, **kw):
    return tc.decode_frame(tc.encode_frame(tensors, **kw))


# -- round-trips ----------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "float64", "int64",
                                   "int32", "uint8", "bool"])
def test_roundtrip_bit_exact_per_dtype(dtype):
    rng = np.random.RandomState(3)
    arr = (rng.randn(5, 7) * 100).astype(dtype)
    out = _rt({"x": arr}).tensors["x"]
    assert out.dtype == np.dtype(dtype)
    assert out.shape == arr.shape
    assert np.array_equal(out, arr)
    # Bit-exact, not just value-equal.
    assert out.tobytes() == arr.tobytes()


def test_roundtrip_bf16_wire_upcasts_to_f32():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    arr = np.linspace(-3, 3, 24, dtype=np.float32).reshape(4, 6)
    blob = tc.encode_frame({"x": arr}, wire_dtype="bfloat16")
    out = tc.decode_frame(blob).tensors["x"]
    assert out.dtype == np.float32
    want = arr.astype(ml_dtypes.bfloat16).astype(np.float32)
    assert np.array_equal(out, want)
    # Half the payload bytes vs the f32 encoding.
    assert len(blob) < len(tc.encode_frame({"x": arr}))


def test_wire_dtype_only_compresses_float32():
    ids = np.arange(9, dtype=np.int64)
    f64 = np.ones(4, np.float64)
    frame = _rt({"ids": ids, "f64": f64}, wire_dtype="bfloat16")
    assert frame.tensors["ids"].dtype == np.int64
    assert np.array_equal(frame.tensors["ids"], ids)
    assert frame.tensors["f64"].dtype == np.float64


@pytest.mark.parametrize("shape", [(), (1,), (0,), (0, 7), (3, 0, 2),
                                   (1, 1, 1)])
def test_odd_and_zero_length_shapes(shape):
    arr = np.zeros(shape, np.float32) + 2.5
    out = _rt({"x": arr}).tensors["x"]
    assert out.shape == shape
    assert np.array_equal(out, arr)


def test_non_contiguous_input_encodes_correctly():
    base = np.arange(24, dtype=np.float32).reshape(4, 6)
    sliced = base[:, ::2]           # non-contiguous view
    out = _rt({"x": sliced}).tensors["x"]
    assert np.array_equal(out, sliced)


def test_receive_views_are_zero_copy():
    arr = np.arange(64, dtype=np.float32)
    blob = tc.encode_frame({"x": arr})
    frame = tc.decode_frame(blob)
    view = frame.tensors["x"]
    # A view over the frame buffer, not a copy (the tentpole claim).
    assert not view.flags.owndata
    assert np.shares_memory(
        view, np.frombuffer(blob, np.uint8))
    # 8-byte aligned offsets: safe typed views for every dtype used.
    assert all(e["offset"] % tc.FRAME_ALIGN == 0
               for e in json.loads(_header_bytes(blob))["tensors"])


def test_header_fields_roundtrip():
    frame = _rt({"x": np.zeros(1, np.float32)}, kind="predict",
                model_version=41, routing_key="user-9",
                meta={"response_wire": "bfloat16"})
    assert frame.kind == "predict"
    assert frame.model_version == 41
    assert frame.routing_key == "user-9"
    assert frame.meta["response_wire"] == "bfloat16"
    # Tensor order preserved (insertion order of the dict).
    multi = _rt([("b", np.zeros(1)), ("a", np.ones(1))])
    assert list(multi.tensors) == ["b", "a"]


def test_content_type_predicate():
    assert tc.is_frame_content_type(tc.FRAME_CONTENT_TYPE)
    assert tc.is_frame_content_type(
        tc.FRAME_CONTENT_TYPE + "; charset=binary")
    assert not tc.is_frame_content_type("application/json")
    assert not tc.is_frame_content_type(None)
    assert not tc.is_frame_content_type("")


# -- refusal: truncation and garbage --------------------------------------

def _header_bytes(blob):
    _, hlen, _ = struct.unpack_from("<4sIQ", blob)
    return blob[tc.FRAME_PREAMBLE_SIZE:tc.FRAME_PREAMBLE_SIZE + hlen]


def _good_blob():
    return tc.encode_frame({"x": np.arange(6, dtype=np.float32),
                            "y": np.arange(4, dtype=np.int64)},
                           kind="predict", routing_key="k")


def test_truncation_refused_at_every_boundary():
    blob = _good_blob()
    # Mid-preamble, exactly-preamble, mid-header, mid-payload, one
    # byte short: every cut raises, none hangs or mis-decodes.
    for cut in (0, 7, tc.FRAME_PREAMBLE_SIZE,
                tc.FRAME_PREAMBLE_SIZE + 3, len(blob) - 1):
        with pytest.raises(tc.FrameError):
            tc.decode_frame(blob[:cut])


def test_trailing_garbage_refused():
    with pytest.raises(tc.FrameError, match="trailing|truncated"):
        tc.decode_frame(_good_blob() + b"x")


def test_garbage_magic_refused():
    blob = _good_blob()
    with pytest.raises(tc.FrameError, match="magic"):
        tc.decode_frame(b"NOPE" + blob[4:])


def test_absurd_header_length_refused():
    bad = struct.pack("<4sIQ", tc.FRAME_MAGIC,
                      tc.FRAME_HEADER_MAX + 1, 0)
    with pytest.raises(tc.FrameError, match="header length"):
        tc.decode_frame(bad)


def test_non_json_header_refused():
    payload = b""
    header = b"\xff\xfe not json"
    blob = struct.pack("<4sIQ", tc.FRAME_MAGIC, len(header),
                       len(payload)) + header + payload
    with pytest.raises(tc.FrameError, match="JSON"):
        tc.decode_frame(blob)


def _frame_with_entry(entry, payload=b"\x00" * 64):
    header = json.dumps({"kind": "t", "model_version": 0,
                         "tensors": [entry]}).encode()
    return (struct.pack("<4sIQ", tc.FRAME_MAGIC, len(header),
                        len(payload)) + header + payload)


def test_tensor_table_out_of_bounds_refused():
    for entry in (
        # nbytes does not match shape*itemsize
        {"name": "x", "dtype": "float32", "shape": [4], "offset": 0,
         "nbytes": 12},
        # runs past the payload
        {"name": "x", "dtype": "float32", "shape": [32], "offset": 8,
         "nbytes": 128},
        # negative offset
        {"name": "x", "dtype": "float32", "shape": [2], "offset": -8,
         "nbytes": 8},
        # negative dim
        {"name": "x", "dtype": "float32", "shape": [-1, 4],
         "offset": 0, "nbytes": 16},
        # unknown dtype
        {"name": "x", "dtype": "notadtype", "shape": [2], "offset": 0,
         "nbytes": 8},
        # not an object at all
        "garbage",
    ):
        with pytest.raises(tc.FrameError):
            tc.decode_frame(_frame_with_entry(entry))


def test_duplicate_tensor_name_refused():
    entry = {"name": "x", "dtype": "float32", "shape": [2],
             "offset": 0, "nbytes": 8}
    header = json.dumps({"kind": "t", "model_version": 0,
                         "tensors": [entry, entry]}).encode()
    blob = struct.pack("<4sIQ", tc.FRAME_MAGIC, len(header),
                       64) + header + b"\x00" * 64
    with pytest.raises(tc.FrameError, match="duplicate"):
        tc.decode_frame(blob)


# -- header-only stream reads (the router's keyed-placement path) ---------

def test_read_frame_header_consumes_exactly_the_header():
    blob = _good_blob()
    fp = io.BytesIO(blob)
    header, prefix, payload_len = tc.read_frame_header(
        fp, limit=len(blob))
    assert header["routing_key"] == "k"
    assert prefix == blob[:len(prefix)]
    assert len(prefix) + payload_len == len(blob)
    # The payload was NOT consumed: splicing prefix + rest reproduces
    # the original bytes exactly (the router's zero-re-encode
    # invariant).
    assert prefix + fp.read() == blob


def test_read_frame_header_limit_mismatch_refused():
    blob = _good_blob()
    with pytest.raises(tc.FrameError, match="transport framed"):
        tc.read_frame_header(io.BytesIO(blob), limit=len(blob) + 5)


def test_read_frame_header_truncated_stream_refused():
    blob = _good_blob()
    with pytest.raises(tc.FrameError, match="truncated"):
        tc.read_frame_header(io.BytesIO(blob[:10]))


# -- pytree flatten/unflatten ---------------------------------------------

def test_tree_spec_roundtrip():
    tree = {"logits": np.arange(4, dtype=np.float32),
            "aux": [np.arange(3, dtype=np.int64),
                    {"scale": np.float32(2.0)}]}
    tensors, spec = tc.flatten_tree(tree)
    rebuilt = tc.unflatten_tree(spec, dict(tensors))
    assert np.array_equal(rebuilt["logits"], tree["logits"])
    assert np.array_equal(rebuilt["aux"][0], tree["aux"][0])
    assert rebuilt["aux"][1]["scale"] == 2.0


def test_tree_spec_missing_tensor_refused():
    with pytest.raises(tc.FrameError, match="missing tensor"):
        tc.unflatten_tree("t", {})


# -- model frames ---------------------------------------------------------

def test_model_frame_roundtrip_with_embeddings():
    dense = {"w": np.arange(8, dtype=np.float32).reshape(2, 4),
             "steps": np.int64(7)}
    emb = {"users": (np.array([3, 11], np.int64),
                     np.ones((2, 4), np.float32))}
    blob = tc.encode_model_frame(dense, emb, version=9)
    d2, e2, version = tc.decode_model_frame(blob)
    assert version == 9
    assert np.array_equal(d2["w"], dense["w"])
    assert np.array_equal(e2["users"][0], emb["users"][0])
    assert np.array_equal(e2["users"][1], emb["users"][1])


def test_model_frame_bf16_wire_halves_dense_payload():
    dense = {"w": np.random.RandomState(0)
             .randn(64, 64).astype(np.float32)}
    full = tc.encode_model_frame(dense, version=1)
    compressed = tc.encode_model_frame(dense, version=1,
                                       wire_dtype="bfloat16")
    assert len(compressed) < 0.6 * len(full)
    d2, _, _ = tc.decode_model_frame(compressed)
    assert d2["w"].dtype == np.float32


def test_model_frame_refuses_other_kinds_and_torn_tables():
    with pytest.raises(tc.FrameError, match="not a model frame"):
        tc.decode_model_frame(
            tc.encode_frame({"x": np.zeros(1)}, kind="predict"))
    # ids without values
    blob = tc.encode_frame({"ei/users": np.arange(2)},
                           kind=tc.MODEL_FRAME_KIND)
    with pytest.raises(tc.FrameError, match="mismatch"):
        tc.decode_model_frame(blob)
    # unprefixed tensor
    blob = tc.encode_frame({"rogue": np.zeros(1)},
                           kind=tc.MODEL_FRAME_KIND)
    with pytest.raises(tc.FrameError, match="prefix"):
        tc.decode_model_frame(blob)


def test_hostile_dtypes_refused_as_frame_errors():
    """dtype "object" resolves via np.dtype (itemsize 8) but
    np.frombuffer raises a PLAIN ValueError for it — the codec must
    refuse it (and every non-numeric dtype) as FrameError so a hostile
    frame stays a 400, never an escaped handler exception."""
    for dtype in ("object", "O", "str", "U8", "S4", "datetime64[s]",
                  "V8"):
        entry = {"name": "x", "dtype": dtype, "shape": [1],
                 "offset": 0,
                 "nbytes": np.dtype(dtype).itemsize or 8}
        with pytest.raises(tc.FrameError):
            tc.decode_frame(_frame_with_entry(entry))
    # ...while bfloat16 (the registered extra) stays frameable.
    ml_dtypes = pytest.importorskip("ml_dtypes")
    arr = np.ones(4, ml_dtypes.bfloat16)
    out = _rt({"x": arr}).tensors["x"]
    assert out.dtype == np.dtype(ml_dtypes.bfloat16)
    assert np.array_equal(out.astype(np.float32),
                          arr.astype(np.float32))


# -- PS data-plane frames (PR 17) -----------------------------------------

def test_grads_frame_roundtrip():
    dense = {"w": np.arange(8, dtype=np.float32).reshape(2, 4),
             "b": np.ones(4, np.float32)}
    emb = {"users": (np.ones((3, 4), np.float32),
                     np.array([5, 2, 5], np.int64))}
    blob = tc.encode_grads_frame(dense=dense, embeddings=emb,
                                 version=6, learning_rate=0.25,
                                 generation=42)
    d2, e2, version, lr = tc.decode_grads_frame(blob)
    assert version == 6 and lr == 0.25
    assert tc.frame_meta(tc.peek_frame_header(blob))["generation"] == 42
    for k in dense:
        assert np.array_equal(d2[k], dense[k])
    vals, ids = e2["users"]
    assert np.array_equal(vals, emb["users"][0])
    assert np.array_equal(np.asarray(ids), emb["users"][1])
    assert ids.dtype == np.int64


def test_grads_frame_bf16_wire_upcasts_and_keeps_ids_exact():
    dense = {"w": np.random.RandomState(3)
             .randn(32, 32).astype(np.float32)}
    emb = {"t": (np.random.RandomState(4)
                 .randn(5, 8).astype(np.float32),
                 np.array([9, 1, 9, 3, 7], np.int64))}
    blob = tc.encode_grads_frame(dense=dense, embeddings=emb,
                                 version=1, wire_dtype="bfloat16")
    d2, e2, _, _ = tc.decode_grads_frame(blob)
    assert d2["w"].dtype == np.float32
    # values round through bf16; ids must NOT be compressed
    assert np.array_equal(
        d2["w"], dense["w"].astype("bfloat16").astype(np.float32))
    assert np.array_equal(np.asarray(e2["t"][1]), emb["t"][1])


def test_grads_frame_refuses_torn_tables_and_bad_meta():
    # values without ids
    blob = tc.encode_frame({"ev/t": np.ones((2, 2), np.float32)},
                           kind=tc.GRADS_FRAME_KIND)
    with pytest.raises(tc.FrameError):
        tc.decode_grads_frame(blob)
    # ids that are not int64 1-D
    blob = tc.encode_frame(
        {"ev/t": np.ones((2, 2), np.float32),
         "ei/t": np.ones((2, 2), np.int64)},
        kind=tc.GRADS_FRAME_KIND)
    with pytest.raises(tc.FrameError):
        tc.decode_grads_frame(blob)
    # row-count mismatch between values and ids
    blob = tc.encode_frame(
        {"ev/t": np.ones((2, 2), np.float32),
         "ei/t": np.arange(3, dtype=np.int64)},
        kind=tc.GRADS_FRAME_KIND)
    with pytest.raises(tc.FrameError):
        tc.decode_grads_frame(blob)
    # meta that lies about its types must stay a FrameError (it is
    # what the servicer maps to INVALID_ARGUMENT)
    blob = tc.encode_frame({"d/w": np.ones(2, np.float32)},
                           kind=tc.GRADS_FRAME_KIND,
                           meta={"learning_rate": ["nope"]})
    with pytest.raises(tc.FrameError):
        tc.decode_grads_frame(blob)
    # wrong kind
    with pytest.raises(tc.FrameError, match="not a gradient frame"):
        tc.decode_grads_frame(
            tc.encode_frame({"x": np.zeros(1)}, kind="predict"))


def test_params_frame_roundtrip_and_tensorless_fast_path():
    dense = {"w": np.arange(6, dtype=np.float32)}
    blob = tc.encode_params_frame(dense, version=11, initialized=True,
                                  generation=5)
    init, version, generation, d2 = tc.decode_params_frame(blob)
    assert init and version == 11 and generation == 5
    assert np.array_equal(d2["w"], dense["w"])
    # not-modified fast path: NO tensors, meta still authoritative
    fast = tc.encode_params_frame(None, version=11, initialized=True,
                                  generation=5)
    init, version, generation, d2 = tc.decode_params_frame(fast)
    assert init and version == 11 and generation == 5 and d2 == {}
    assert len(fast) < 200  # header-only
    with pytest.raises(tc.FrameError):
        tc.decode_params_frame(
            tc.encode_frame({}, kind="predict"))
    # non-integer generation in meta is a FrameError, not a TypeError
    lying = tc.encode_frame({}, kind=tc.PARAMS_FRAME_KIND,
                            meta={"generation": {"evil": 1}})
    with pytest.raises(tc.FrameError):
        tc.decode_params_frame(lying)


# -- decode-copy accounting (the bench gate's arithmetic) -----------------

def test_decode_copy_accounting_pb_vs_frame():
    from elasticdl_tpu.proto import elastic_pb2 as pb

    arr = np.random.RandomState(0).randn(100).astype(np.float32)
    # pb at full precision: one copy-out of the content bytes
    t = tc.ndarray_to_pb(arr)
    assert tc.pb_decode_copy_bytes(t) == arr.nbytes
    # pb at bf16 wire: copy-out of 2-byte content PLUS the 4-byte
    # upcast materialization = 3 passes over the logical payload
    t16 = tc.ndarray_to_pb(arr, wire_dtype="bfloat16")
    assert tc.pb_decode_copy_bytes(t16) == 100 * 2 + 100 * 4
    # frame at full precision: views are free
    blob = tc.encode_frame({"x": arr})
    assert tc.frame_decode_copy_bytes(tc.peek_frame_header(blob)) == 0
    # frame at bf16 wire: only the upcast is a copy
    blob16 = tc.encode_frame({"x": arr}, wire_dtype="bfloat16")
    assert tc.frame_decode_copy_bytes(
        tc.peek_frame_header(blob16)) == 100 * 4
    # model-level pb accounting adds the ids' int64 materialization
    m = pb.ModelPB()
    tc.indexed_slices_to_pb(np.ones((4, 2), np.float32),
                            np.arange(4, dtype=np.int64),
                            out=m.embedding_tables["e"])
    assert tc.model_pb_decode_copy_bytes(m) == 4 * 2 * 4 + 4 * 8


def test_peek_frame_header_validates_total_length():
    blob = tc.encode_frame({"x": np.ones(4, np.float32)}, kind="k",
                           meta={"generation": 3})
    header = tc.peek_frame_header(blob)
    assert header["kind"] == "k"
    assert tc.frame_meta(header) == {"generation": 3}
    with pytest.raises(tc.FrameError, match="truncated"):
        tc.peek_frame_header(blob[:-1])
    with pytest.raises(tc.FrameError, match="truncated|trailing"):
        tc.peek_frame_header(blob + b"\x00")
