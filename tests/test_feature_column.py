"""Feature columns: offset concatenation, analyzer-stat plumbing, and an
end-to-end feed through the PS-served DeepFM path (reference:
elasticdl_preprocessing/feature_column/feature_column.py, in particular
the concatenated_categorical_column id-offset example)."""

import numpy as np

from elasticdl_tpu.preprocessing import analyzer_utils
from elasticdl_tpu.preprocessing.feature_column import (
    BucketizedColumn,
    CategoricalHashColumn,
    CategoricalIdentityColumn,
    CategoricalVocabColumn,
    NumericColumn,
    concatenated_categorical_column,
    make_feed,
)


def test_concatenated_column_offsets_match_reference_example():
    """The reference docstring's worked example: identity(32) +
    vocab(["Private", "Self-emp-inc"]) — second column's ids offset
    by 32."""
    id_col = CategoricalIdentityColumn("id", num_buckets=32)
    work = CategoricalVocabColumn(
        "work_class", ["Private", "Self-emp-inc"]
    )
    concat = concatenated_categorical_column([id_col, work])
    assert concat.num_buckets == 32 + 3  # 32 + vocab 2 + oov 1
    ids = concat.transform({
        "id": [1, 0, 8],
        "work_class": ["", "Private", "Self-emp-inc"],
    })
    assert ids.shape == (3, 2)
    np.testing.assert_array_equal(ids[:, 0], [1, 0, 8])
    # "" -> OOV (=2) + offset 32 = 34; Private -> 0+32; Self-emp-inc -> 1+32
    np.testing.assert_array_equal(ids[:, 1], [34, 32, 33])


def test_hash_and_bucketized_columns():
    h = CategoricalHashColumn("city", 16)
    ids = h.transform(["sf", "nyc", "sf"])
    assert ids.shape == (3,) and (ids < 16).all() and (ids >= 0).all()
    assert ids[0] == ids[2]
    b = BucketizedColumn("age", [25, 50])
    np.testing.assert_array_equal(
        b.transform([18, 30, 77]), [0, 1, 2]
    )
    assert b.num_buckets == 3


def test_from_stats_env_plumbing(monkeypatch):
    """An analyzer job exports stats into the env; columns configure
    themselves from them (reference _ELASTICDL_* scheme)."""
    analyzer_utils.set_stats("age", avg=40.0, stddev=10.0,
                            bucket_boundaries=[25, 50])
    analyzer_utils.set_stats("work_class", vocabulary=["a", "b"])
    try:
        n = NumericColumn.from_stats("age")
        np.testing.assert_allclose(n.transform([50.0]), [1.0])
        b = BucketizedColumn.from_stats("age")
        np.testing.assert_array_equal(b.transform([30.0]), [1])
        v = CategoricalVocabColumn.from_stats("work_class")
        np.testing.assert_array_equal(v.transform(["b", "zz"]), [1, 2])
    finally:
        import os

        for k in list(os.environ):
            if k.startswith("_EDL_TPU_"):
                del os.environ[k]


def test_make_feed_emits_framework_convention():
    feed = make_feed(
        numeric_columns=[NumericColumn("hours")],
        id_tables={
            "emb": concatenated_categorical_column([
                CategoricalIdentityColumn("id", 8),
                CategoricalHashColumn("city", 8),
            ]),
        },
    )
    records = [
        {"hours": 40, "id": 3, "city": "sf", "label": 1},
        {"hours": 20, "id": 5, "city": "nyc", "label": 0},
    ]
    features, labels = feed(records)
    assert features["dense"].shape == (2, 1)
    assert features["__ids__"]["emb"].shape == (2, 2)
    assert (features["__ids__"]["emb"][:, 1] >= 8).all()  # offset applied
    np.testing.assert_array_equal(labels, [1, 0])


def test_feature_column_feed_trains_through_ps():
    """End to end: a feature-column feed drives the PS embedding path
    (pull unique rows, push sparse grads) for a tiny linear model."""
    import jax.numpy as jnp
    import optax

    from elasticdl_tpu.models.spec import ModelSpec
    from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer
    from tests.test_pserver import start_ps, stop_all

    concat = concatenated_categorical_column([
        CategoricalIdentityColumn("id", 16),
        CategoricalHashColumn("city", 16),
    ])
    feed = make_feed(
        numeric_columns=[NumericColumn("hours")],
        id_tables={"fc_emb": concat},
    )

    def apply_fn(params, feats, train):
        rows = feats["emb__fc_emb"][feats["idx__fc_emb"]]  # [B,F,4]
        x = jnp.concatenate(
            [rows.reshape(rows.shape[0], -1), feats["dense"]], axis=-1
        )
        return (x @ params["w"])[:, 0]

    spec = ModelSpec(
        name="fc_linear",
        init_fn=lambda rng: {
            "w": jnp.zeros((2 * 4 + 1, 1), jnp.float32)
        },
        apply_fn=apply_fn,
        loss_fn=lambda logits, labels: optax.sigmoid_binary_cross_entropy(
            logits, labels.astype(jnp.float32)
        ),
        optimizer=optax.sgd(0.1),
        feed=feed,
        ps_embedding_infos=[
            {"name": "fc_emb", "dim": 4, "initializer": "zeros"}
        ],
        ps_optimizer=("sgd", "learning_rate=0.1"),
    )
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=0.1"
    )
    try:
        trainer = ParameterServerTrainer(spec, client, batch_size=4)
        records = [
            {"hours": float(i), "id": i % 16, "city": "c%d" % (i % 3),
             "label": i % 2}
            for i in range(4)
        ]
        features, labels = feed(records)
        loss1, _ = trainer.train_minibatch(features, labels)
        loss2, _ = trainer.train_minibatch(features, labels)
        assert np.isfinite(loss1) and np.isfinite(loss2)
        assert loss2 < loss1  # embeddings + dense actually learn
    finally:
        stop_all(servers)


def test_vocab_column_handles_bytes_and_nesting_rejected():
    v = CategoricalVocabColumn("w", ["Private", "Self-emp-inc"])
    np.testing.assert_array_equal(
        v.transform([b"Private", "Self-emp-inc", b"zz"]), [0, 1, 2]
    )
    import pytest

    from elasticdl_tpu.preprocessing.feature_column import (
        ConcatenatedCategoricalColumn,
    )

    inner = concatenated_categorical_column(
        [CategoricalIdentityColumn("a", 4)]
    )
    with pytest.raises(ValueError, match="nest"):
        ConcatenatedCategoricalColumn(
            [inner, CategoricalIdentityColumn("b", 4)]
        )


def test_hash_column_int_values_vectorized_path():
    h = CategoricalHashColumn("uid", 32)
    ids = h.transform(np.arange(100, dtype=np.int64))
    assert ids.shape == (100,) and (ids < 32).all()
