"""Ring attention vs local reference attention on a virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.parallel.ring_attention import (
    attention_local,
    ring_attention,
)


def make_qkv(b=2, t=32, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, t, h, d)
    q = rng.randn(*shape).astype(np.float32)
    k = rng.randn(*shape).astype(np.float32)
    v = rng.randn(*shape).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4])
def test_ring_matches_local(causal, sp):
    q, k, v = make_qkv()
    mesh = build_mesh(dp=2, tp=1, sp=sp,
                      devices=jax.devices()[: 2 * sp])
    ref = attention_local(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_ring_with_tp_sharded_heads():
    q, k, v = make_qkv(b=2, t=16, h=4, d=8)
    mesh = build_mesh(dp=2, tp=2, sp=2, devices=jax.devices())
    ref = attention_local(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_ring_inside_jit_grad():
    """Differentiable and jittable — required for the training path."""
    q, k, v = make_qkv(b=2, t=16, h=2, d=8)
    mesh = build_mesh(dp=1, tp=1, sp=4, devices=jax.devices()[:4])

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh).sum()

    def loss_ref(q, k, v):
        return attention_local(q, k, v).sum()

    g = jax.jit(jax.grad(loss))(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_ring_flash_fold_matches_local(monkeypatch):
    """Ring attention with the Pallas partial kernel in the fold
    (ELASTICDL_FLASH=interpret) matches the local reference, causal and
    not."""
    monkeypatch.setenv("ELASTICDL_FLASH", "interpret")
    from elasticdl_tpu.parallel import ring_attention as ra

    mesh = build_mesh(sp=4, dp=2)
    rng = np.random.RandomState(7)
    # t=512 over sp=4 -> 128-row shards, flash-friendly; d=64
    q, k, v = (
        jnp.asarray(rng.randn(2, 512, 2, 64).astype(np.float32))
        for _ in range(3)
    )
    for causal in (True, False):
        got = ra.ring_attention(q, k, v, mesh, causal=causal)
        monkeypatch.setenv("ELASTICDL_FLASH", "off")
        want = ra.attention_local(q, k, v, causal=causal)
        monkeypatch.setenv("ELASTICDL_FLASH", "interpret")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


def test_flash_path_stays_partitioned_under_dp_mesh(monkeypatch):
    """The pallas kernel must run inside a manual shard_map over dp/tp:
    under plain GSPMD it would be all-gathered and replicated (review
    r2 finding). Assert the jitted output keeps its dp sharding."""
    monkeypatch.setenv("ELASTICDL_FLASH", "interpret")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from elasticdl_tpu.parallel import ring_attention as ra

    mesh = build_mesh(dp=2, tp=2, ep=2)  # sp=1: the flash hot path
    rng = np.random.RandomState(9)
    spec = P("dp", None, "tp", None)
    q, k, v = (
        jax.device_put(
            jnp.asarray(rng.randn(4, 128, 4, 64).astype(np.float32)),
            NamedSharding(mesh, spec),
        )
        for _ in range(3)
    )

    @jax.jit
    def f(q, k, v):
        return ra.ring_attention(q, k, v, mesh, causal=True)

    out = f(q, k, v)
    assert out.sharding.spec == spec, (
        "flash path lost its partitioning: %s" % (out.sharding,)
    )
    monkeypatch.setenv("ELASTICDL_FLASH", "off")
    want = ra.attention_local(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_ring_long_context_sp8():
    """Long-context shape: T=2048 sharded 8 ways — each device holds a
    256-token block and the T x T matrix never exists on one device.
    Output parity vs single-device attention."""
    mesh = build_mesh(sp=8)
    rng = np.random.RandomState(11)
    q, k, v = (
        jnp.asarray(rng.randn(1, 2048, 2, 32).astype(np.float32))
        for _ in range(3)
    )
    from elasticdl_tpu.parallel import ring_attention as ra

    got = ra.ring_attention(q, k, v, mesh, causal=True)
    want = ra.attention_local(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)
