"""Native kernel parity vs golden numpy updates (reference pattern:
go/pkg/kernel/kernel_test.go:25-182)."""

import numpy as np
import pytest

nb = pytest.importorskip("elasticdl_tpu.native.bindings")


def test_sgd_matches_numpy():
    rng = np.random.RandomState(0)
    p = rng.randn(100).astype(np.float32)
    g = rng.randn(100).astype(np.float32)
    expect = p - 0.1 * g
    nb.sgd(p, g, 0.1)
    np.testing.assert_allclose(p, expect, rtol=1e-6)


def test_momentum_matches_numpy():
    rng = np.random.RandomState(1)
    p = rng.randn(50).astype(np.float32)
    g = rng.randn(50).astype(np.float32)
    vel = np.zeros(50, np.float32)
    p0 = p.copy()
    nb.momentum(p, g, vel, lr=0.1, mu=0.9)
    np.testing.assert_allclose(vel, g, rtol=1e-6)
    np.testing.assert_allclose(p, p0 - 0.1 * g, rtol=1e-6)
    # second step accumulates velocity
    p1 = p.copy()
    nb.momentum(p, g, vel, lr=0.1, mu=0.9)
    np.testing.assert_allclose(vel, 0.9 * g + g, rtol=1e-6)
    np.testing.assert_allclose(p, p1 - 0.1 * (0.9 * g + g), rtol=1e-5)


def test_adam_bias_correction_matches_numpy():
    rng = np.random.RandomState(2)
    p = rng.randn(64).astype(np.float32)
    g = rng.randn(64).astype(np.float32)
    m = np.zeros(64, np.float32)
    v = np.zeros(64, np.float32)
    p_ref, m_ref, v_ref = p.copy(), m.copy(), v.copy()
    lr, b1, b2, eps = 0.001, 0.9, 0.999, 1e-8
    for step in range(1, 4):
        nb.adam(p, g, m, v, lr, step, b1, b2, eps)
        m_ref = b1 * m_ref + (1 - b1) * g
        v_ref = b2 * v_ref + (1 - b2) * g * g
        alpha = lr * np.sqrt(1 - b2 ** step) / (1 - b1 ** step)
        p_ref = p_ref - alpha * m_ref / (np.sqrt(v_ref) + eps)
    np.testing.assert_allclose(p, p_ref, rtol=1e-5)


def test_adam_amsgrad():
    p = np.ones(4, np.float32)
    m = np.zeros(4, np.float32)
    v = np.zeros(4, np.float32)
    maxsq = np.zeros(4, np.float32)
    g1 = np.full(4, 2.0, np.float32)
    g2 = np.full(4, 0.01, np.float32)
    nb.adam(p, g1, m, v, 0.01, 1, max_square=maxsq)
    v_after_1 = v.copy()
    nb.adam(p, g2, m, v, 0.01, 2, max_square=maxsq)
    # max_square holds the peak v, not the decayed one
    np.testing.assert_allclose(maxsq, v_after_1, rtol=1e-6)
    assert (v < maxsq).all()


def test_adagrad_matches_numpy():
    p = np.ones(8, np.float32)
    g = np.full(8, 0.5, np.float32)
    accum = np.zeros(8, np.float32)
    nb.adagrad(p, g, accum, lr=0.1)
    np.testing.assert_allclose(accum, 0.25, rtol=1e-6)
    np.testing.assert_allclose(p, 1 - 0.1 * 0.5 / (0.5 + 1e-8),
                               rtol=1e-5)


def test_table_lazy_init_deterministic():
    t1 = nb.NativeEmbeddingTable(4, "uniform", seed=42)
    t2 = nb.NativeEmbeddingTable(4, "uniform", seed=42)
    np.testing.assert_array_equal(t1.get([3, 7]), t2.get([7, 3])[::-1])
    assert len(t1) == 2
    bounds = t1.get([99])
    assert (bounds >= -0.05).all() and (bounds <= 0.05).all()


def test_table_set_get_export():
    t = nb.NativeEmbeddingTable(3, "zeros")
    vals = np.arange(6, dtype=np.float32).reshape(2, 3)
    t.set([10, 20], vals)
    np.testing.assert_array_equal(t.get([20, 10]), vals[::-1])
    ids, values = t.export()
    order = np.argsort(ids)
    np.testing.assert_array_equal(ids[order], [10, 20])
    np.testing.assert_array_equal(values[order], vals)


def test_table_sparse_adam_matches_dense_adam():
    t = nb.NativeEmbeddingTable(4, "zeros")
    m_t = nb.NativeEmbeddingTable(4, "zeros")
    v_t = nb.NativeEmbeddingTable(4, "zeros")
    row0 = np.random.RandomState(3).randn(1, 4).astype(np.float32)
    t.set([5], row0)
    g = np.full((1, 4), 0.3, np.float32)
    t.apply_adam([5], g, m_t, v_t, lr=0.01, step=1)

    p = row0[0].copy()
    m = np.zeros(4, np.float32)
    v = np.zeros(4, np.float32)
    nb.adam(p, g[0], m, v, 0.01, 1)
    np.testing.assert_allclose(t.get([5])[0], p, rtol=1e-6)
