"""Seeded ABBA-deadlock fixture for elastic-lint EL005 + the runtime
tracer's lock-order edges.

Two ledgers each take their OWN lock and then call into the peer,
which takes ITS lock — opposite orders on the two paths.  Two threads
entering simultaneously (alpha.credit_via_beta vs
beta.credit_via_alpha) deadlock: classic ABBA.  EL005 must flag the
cycle statically, and ``drive_abba_sequentially`` exercises both
orderings on ONE thread so the tracer records the A->B and B->A edges
(and the cycle) without ever actually deadlocking the test process.

This module lives in tests/ (outside the lint gate) precisely so the
seeded bug stays seeded.
"""

import threading


class LedgerAlpha:
    def __init__(self, ledger_beta=None):
        self._lock = threading.Lock()
        self._ledger_beta = ledger_beta
        self._balance = 0

    def credit(self):
        with self._lock:
            self._balance += 1

    def credit_via_beta(self):
        # Holds alpha's lock while acquiring beta's: A -> B.
        with self._lock:
            self._balance -= 1
            self._ledger_beta.credit()


class LedgerBeta:
    def __init__(self, ledger_alpha=None):
        self._lock = threading.Lock()
        self._ledger_alpha = ledger_alpha
        self._balance = 0

    def credit(self):
        with self._lock:
            self._balance += 1

    def credit_via_alpha(self):
        # Holds beta's lock while acquiring alpha's: B -> A.  Combined
        # with credit_via_beta this closes the ABBA cycle.
        with self._lock:
            self._balance -= 1
            self._ledger_alpha.credit()


def build_pair():
    alpha = LedgerAlpha()
    beta = LedgerBeta(ledger_alpha=alpha)
    alpha._ledger_beta = beta
    return alpha, beta


def drive_abba_sequentially(alpha, beta):
    """Exercise BOTH acquisition orders on the calling thread — the
    tracer observes the A->B and B->A edges (a runtime-confirmed
    cycle) while the single thread guarantees no actual deadlock."""
    alpha.credit_via_beta()
    beta.credit_via_alpha()
