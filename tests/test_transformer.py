"""Flagship transformer: forward parity across parallelism layouts, and a
full 4-axis (dp/pp/tp/sp) train step on the virtual 8-device mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.models import transformer as tfm
from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SPMDTrainer

CFG = tfm.TransformerConfig(
    vocab_size=128, dim=64, num_heads=4, num_layers=2,
    max_seq_len=32, dtype="float32",
)


def make_tokens(b=4, t=32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, CFG.vocab_size, size=(b, t)).astype(np.int32)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def test_forward_shapes_and_finite(params):
    tokens = make_tokens()
    logits = tfm.forward(params, tokens, CFG)
    assert logits.shape == (4, 32, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize(
    "axes", [dict(dp=2, tp=2, sp=2), dict(dp=1, tp=4, sp=2),
             dict(dp=8, tp=1, sp=1), dict(dp=1, pp=2, tp=2, sp=2)]
)
def test_sharded_forward_matches_single_device(params, axes):
    tokens = make_tokens()
    ref = np.asarray(tfm.forward(params, tokens, CFG))
    mesh = build_mesh(**axes)
    sharded = tfm.shard_params(params, mesh, CFG)
    out = jax.jit(
        lambda p, t: tfm.forward(p, t, CFG, mesh=mesh)
    )(sharded, tokens)
    np.testing.assert_allclose(ref, np.asarray(out), rtol=5e-4, atol=5e-4)


@pytest.mark.parametrize("chunk", [8, 12, 32])
def test_chunked_xent_matches_dense(params, chunk):
    """next_token_loss_chunked == next_token_loss(_head(hidden)) in
    value AND gradients — incl. chunk=12 (T-1=31 pads to 36) and
    chunk=32 (single padded chunk).  This is the no-[B,T,V]-logits
    training path the flagship LM bench uses."""
    tokens = make_tokens(b=2, t=32, seed=3)

    def dense_loss(p):
        logits = tfm.forward(p, tokens, CFG)
        return tfm.next_token_loss(logits, tokens).mean()

    def chunked_loss(p):
        hidden, _aux = tfm.forward_hidden(p, tokens, CFG)
        return tfm.next_token_loss_chunked(
            p, hidden, tokens, CFG, chunk=chunk
        ).mean()

    l0, g0 = jax.value_and_grad(dense_loss)(params)
    l1, g1 = jax.jit(jax.value_and_grad(chunked_loss))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                atol=1e-6),
        g0, g1,
    )


def test_model_spec_xent_chunk_trains_like_dense():
    """model_spec(xent_chunk=N) is a product option: same loss as the
    dense spec through a real CollectiveTrainer minibatch."""
    from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

    kwargs = dict(vocab_size=64, dim=32, num_heads=2, num_layers=2,
                  seq_len=16, dtype="float32")
    toks = np.random.RandomState(1).randint(
        0, 64, size=(4, 16)).astype(np.int32)
    losses = {}
    for name, extra in (("dense", {}), ("chunked", {"xent_chunk": 8})):
        spec = tfm.model_spec(**kwargs, **extra)
        trainer = CollectiveTrainer(spec, batch_size=4)
        loss, _ = trainer.train_minibatch(toks, toks)
        losses[name] = float(loss)
    assert np.isfinite(losses["chunked"])
    np.testing.assert_allclose(losses["chunked"], losses["dense"],
                               rtol=1e-5)


def test_gqa_equals_mha_with_tiled_kv_weights():
    """GQA correctness by construction: a GQA forward must EXACTLY
    equal the MHA forward whose wk/wv are the GQA weights tile-repeated
    per group (k_mha = repeat(k_gqa) by definition)."""
    cfg_g = dataclasses.replace(CFG, num_kv_heads=2)  # H=4, G=2
    params_g = tfm.init_params(jax.random.PRNGKey(3), cfg_g)
    L, E = CFG.num_layers, CFG.dim
    H, D, G = CFG.num_heads, CFG.head_dim, 2
    params_m = jax.tree_util.tree_map(lambda x: x, params_g)  # copy refs
    for name in ("wk", "wv"):
        w = np.asarray(params_g["layers"][name]).reshape(L, E, G, D)
        params_m["layers"][name] = jnp.asarray(
            np.repeat(w, H // G, axis=2).reshape(L, E, H * D)
        )
    tokens = make_tokens(b=2, t=32, seed=4)
    out_g = tfm.forward(params_g, tokens, cfg_g)
    out_m = tfm.forward(params_m, tokens, CFG)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_m),
                               rtol=1e-6, atol=1e-6)


def test_gqa_sharded_matches_single_device():
    """GQA under dp/tp/sp sharding matches the single-device forward."""
    cfg_g = dataclasses.replace(CFG, num_kv_heads=2)
    params_g = tfm.init_params(jax.random.PRNGKey(3), cfg_g)
    tokens = make_tokens()
    ref = np.asarray(tfm.forward(params_g, tokens, cfg_g))
    mesh = build_mesh(dp=2, tp=2, sp=2)
    sharded = tfm.shard_params(params_g, mesh, cfg_g)
    out = jax.jit(
        lambda p, t: tfm.forward(p, t, cfg_g, mesh=mesh)
    )(sharded, tokens)
    np.testing.assert_allclose(ref, np.asarray(out), rtol=5e-4,
                               atol=5e-4)


def test_gqa_trains_and_validates():
    from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

    spec = tfm.model_spec(vocab_size=64, dim=32, num_heads=4,
                          num_layers=2, seq_len=16, dtype="float32",
                          num_kv_heads=2)
    assert spec.config.kv_heads == 2
    toks = make_tokens(b=4, t=16, seed=6)
    trainer = CollectiveTrainer(spec, batch_size=4)
    loss, _ = trainer.train_minibatch(toks % 64, toks % 64)
    assert np.isfinite(float(loss))
    with pytest.raises(ValueError, match="num_kv_heads"):
        tfm.model_spec(vocab_size=64, dim=32, num_heads=4,
                       num_layers=2, seq_len=16, num_kv_heads=3)


def _rollout_reference(params, cfg, prompt, max_new):
    """Teacher-forced greedy rollout through the FULL forward — the
    no-cache reference generate() must match exactly."""
    tokens = np.asarray(prompt)
    for _ in range(max_new):
        logits = tfm.forward(params, jnp.asarray(tokens), cfg)
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
        tokens = np.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


@pytest.mark.parametrize(
    "variant", ["dense", "gqa", "window", "gqa+window"])
def test_generate_matches_full_forward(variant):
    """KV-cache decoding == full-forward greedy rollout, token for
    token (prefill + decode through the cache vs recomputing the whole
    prefix each step)."""
    cfg = {
        "dense": CFG,
        "gqa": dataclasses.replace(CFG, num_kv_heads=2),
        "window": dataclasses.replace(CFG, window=8),
        # Grouped decode einsum x window mask is the interaction with
        # no other exact-match coverage (advisor r4).
        "gqa+window": dataclasses.replace(CFG, num_kv_heads=2,
                                          window=8),
    }[variant]
    params = tfm.init_params(jax.random.PRNGKey(7), cfg)
    prompt = make_tokens(b=2, t=5, seed=8)
    got = np.asarray(
        jax.jit(
            lambda p, t: tfm.generate(p, cfg, t, max_new_tokens=6)
        )(params, prompt)
    )
    want = _rollout_reference(params, cfg, prompt, 6)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got[:, :5], np.asarray(prompt))


def test_generate_moe_and_sampling():
    """MoE decode is finite/valid (exactness vs forward is not expected:
    T=1 decode never hits expert-capacity truncation); temperature
    sampling stays in-vocab and respects the prompt."""
    cfg = dataclasses.replace(CFG, moe_experts=2)
    params = tfm.init_params(jax.random.PRNGKey(9), cfg)
    prompt = make_tokens(b=2, t=4, seed=10)
    out = np.asarray(tfm.generate(params, cfg, prompt, max_new_tokens=5,
                                  temperature=0.8,
                                  rng=jax.random.PRNGKey(1)))
    assert out.shape == (2, 9)
    np.testing.assert_array_equal(out[:, :4], np.asarray(prompt))
    assert ((out >= 0) & (out < CFG.vocab_size)).all()


def test_generate_edge_cases():
    params = tfm.init_params(jax.random.PRNGKey(7), CFG)
    prompt = make_tokens(b=2, t=3, seed=11)
    # max_new_tokens=0 -> the prompt back
    np.testing.assert_array_equal(
        np.asarray(tfm.generate(params, CFG, prompt, 0)),
        np.asarray(prompt))
    # one new token == full-forward argmax at the last prompt position
    out = np.asarray(tfm.generate(params, CFG, prompt, 1))
    want = np.asarray(jnp.argmax(
        tfm.forward(params, jnp.asarray(prompt), CFG)[:, -1], axis=-1))
    np.testing.assert_array_equal(out[:, -1], want)
    # empty prompt is rejected with a BOS hint
    with pytest.raises(ValueError, match="BOS"):
        tfm.generate(params, CFG, np.zeros((2, 0), np.int32), 4)


def test_lm_train_export_reload_generate(tmp_path):
    """The full flagship loop: train a step, export the servable,
    reload the weights from the npz, and generate — reloaded params
    produce the exact same greedy continuation."""
    from elasticdl_tpu.models.callbacks import ModelExporter, load_export
    from elasticdl_tpu.utils.pytree import unflatten_from_names
    from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

    spec = tfm.model_spec(vocab_size=64, dim=32, num_heads=2,
                          num_layers=2, seq_len=16, dtype="float32")
    trainer = CollectiveTrainer(spec, batch_size=4)
    toks = make_tokens(b=4, t=16, seed=12) % 64
    trainer.train_minibatch(toks, toks)
    export_dir = str(tmp_path / "export")
    ModelExporter(export_dir, model_name="lm").on_train_end(trainer)

    dense, _ = load_export(export_dir)
    reloaded = unflatten_from_names(trainer.params, dense)
    prompt = toks[:2, :4]
    out_live = np.asarray(
        tfm.generate(trainer.params, spec.config, prompt, 5))
    out_reloaded = np.asarray(
        tfm.generate(reloaded, spec.config, prompt, 5))
    np.testing.assert_array_equal(out_live, out_reloaded)


def test_model_spec_remat_validation():
    """CLI model_params arrive as strings: booleans normalize, typos
    raise instead of silently enabling full remat."""
    spec = tfm.model_spec(vocab_size=64, dim=32, num_heads=2,
                          num_layers=2, seq_len=16, remat="False")
    assert spec.config.remat is False
    spec = tfm.model_spec(vocab_size=64, dim=32, num_heads=2,
                          num_layers=2, seq_len=16, remat="attn")
    assert spec.config.remat == "attn"
    with pytest.raises(ValueError, match="remat"):
        tfm.model_spec(vocab_size=64, dim=32, num_heads=2,
                       num_layers=2, seq_len=16, remat="atn")


def test_model_spec_xent_chunk_pipelined_matches_dense():
    """xent_chunk works ON the pipelined path (the head runs on merged
    hidden states outside the pipeline) — same loss as dense pipelined."""
    mesh = build_mesh(pp=2, devices=jax.devices()[:2])
    kwargs = dict(vocab_size=64, dim=32, num_heads=2, num_layers=2,
                  seq_len=16, dtype="float32", mesh=mesh,
                  pipeline_microbatches=2)
    toks = make_tokens(b=4, t=16, seed=5)
    spec_d = tfm.model_spec(**kwargs)
    spec_c = tfm.model_spec(**kwargs, xent_chunk=8)
    params_d = spec_d.init_fn(jax.random.PRNGKey(0))
    loss_d = spec_d.loss_fn(spec_d.apply_fn(params_d, toks, True), toks)
    params_c = spec_c.init_fn(jax.random.PRNGKey(0))
    loss_c = spec_c.loss_fn(spec_c.apply_fn(params_c, toks, True), toks)
    np.testing.assert_allclose(np.asarray(loss_d), np.asarray(loss_c),
                               rtol=1e-5)


@pytest.mark.parametrize("remat", [True, "attn", "dots"])
def test_remat_policies_preserve_gradients(params, remat):
    tokens = make_tokens(b=2, t=16)
    cfg_r = dataclasses.replace(CFG, remat=remat)

    def loss(cfg):
        def f(p):
            logits = tfm.forward(p, tokens, cfg)
            return tfm.next_token_loss(logits, tokens).mean()
        return f

    l0, g0 = jax.value_and_grad(loss(CFG))(params)
    l1, g1 = jax.value_and_grad(loss(cfg_r))(params)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                atol=1e-5),
        g0, g1,
    )


def test_ulysses_forward_matches_single_device(params):
    # Same sharded-parity check with the all-to-all sequence-parallel
    # path selected (attention_impl="ulysses", parallel/ulysses.py).
    tokens = make_tokens()
    cfg = dataclasses.replace(CFG, attention_impl="ulysses")
    ref = np.asarray(tfm.forward(params, tokens, cfg))
    mesh = build_mesh(dp=2, tp=2, sp=2)
    sharded = tfm.shard_params(params, mesh, cfg)
    out = jax.jit(
        lambda p, t: tfm.forward(p, t, cfg, mesh=mesh)
    )(sharded, tokens)
    np.testing.assert_allclose(ref, np.asarray(out), rtol=5e-4,
                               atol=5e-4)


MOE_CFG = tfm.TransformerConfig(
    vocab_size=128, dim=64, num_heads=4, num_layers=2,
    max_seq_len=32, dtype="float32", moe_experts=4,
)


def test_moe_forward_matches_across_sharding():
    params = tfm.init_params(jax.random.PRNGKey(3), MOE_CFG)
    tokens = make_tokens(b=4)
    ref = np.asarray(tfm.forward(params, tokens, MOE_CFG))
    assert np.isfinite(ref).all()
    mesh = build_mesh(dp=1, ep=2, tp=2, sp=2)
    sharded = tfm.shard_params(params, mesh, MOE_CFG)
    out = jax.jit(
        lambda p, t: tfm.forward(p, t, MOE_CFG, mesh=mesh)
    )(sharded, tokens)
    np.testing.assert_allclose(ref, np.asarray(out), rtol=5e-4,
                               atol=5e-4)


def test_moe_ep_train_step_learns():
    mesh = build_mesh(dp=1, ep=2, tp=2, sp=2)

    def loss_fn(params, batch):
        tokens, _ = batch
        logits = tfm.forward(params, tokens, MOE_CFG, mesh=mesh)
        return tfm.next_token_loss(logits, tokens).mean()

    trainer = SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, MOE_CFG),
        loss_fn=loss_fn,
        optimizer=optax.adamw(2e-3),
        param_specs=tfm.param_specs(MOE_CFG),
        batch_spec=P("dp", "sp"),
    )
    tokens = make_tokens(b=4)
    losses = [float(trainer.train_step((tokens, tokens)))
              for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_full_4axis_train_step():
    mesh = build_mesh(dp=1, pp=2, tp=2, sp=2)

    def loss_fn(params, batch):
        tokens, _ = batch
        logits = tfm.forward(params, tokens, CFG, mesh=mesh)
        return tfm.next_token_loss(logits, tokens).mean()

    trainer = SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, CFG),
        loss_fn=loss_fn,
        optimizer=optax.adamw(1e-3),
        param_specs=tfm.param_specs(CFG),
        batch_spec=P("dp", "sp"),
    )
    tokens = make_tokens(b=4)
    losses = [float(trainer.train_step((tokens, tokens))) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], f"no learning: {losses}"


def test_loss_decreases_matches_unsharded_trajectory():
    """dp/tp/sp sharded training must follow the single-device trajectory."""
    tokens = make_tokens(b=4)
    tx = optax.sgd(0.1)

    def make_loss(mesh):
        def loss_fn(params, batch):
            toks, _ = batch
            logits = tfm.forward(params, toks, CFG, mesh=mesh)
            return tfm.next_token_loss(logits, toks).mean()
        return loss_fn

    # single device
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    loss_single = make_loss(None)
    opt = tx.init(params)
    traj_single = []
    p = params
    for _ in range(3):
        l, g = jax.value_and_grad(loss_single)(p, (tokens, tokens))
        u, opt = tx.update(g, opt, p)
        p = optax.apply_updates(p, u)
        traj_single.append(float(l))

    mesh = build_mesh(dp=2, tp=2, sp=2)
    trainer = SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, CFG),
        loss_fn=make_loss(mesh),
        optimizer=tx,
        param_specs=tfm.param_specs(CFG),
        batch_spec=P("dp", "sp"),
        rng_seed=1,
    )
    traj_sharded = [
        float(trainer.train_step((tokens, tokens))) for _ in range(3)
    ]
    np.testing.assert_allclose(traj_single, traj_sharded, rtol=2e-3)


def test_moe_aux_loss_signals_imbalance():
    """The Switch load-balance aux: ~1.0 for a near-uniform router, ~X
    under collapse (all tokens AND all probability mass on one expert)
    — minimizing it pushes toward uniform utilization."""
    rng = np.random.RandomState(0)
    B, T, E, X, F = 2, 16, 8, 4, 16
    cfg = tfm.TransformerConfig(
        vocab_size=16, dim=E, num_heads=1, num_layers=1,
        mlp_ratio=2, dtype="float32", moe_experts=X, moe_top_k=2,
    )
    # positive activations so a positive router column really dominates
    h = jnp.asarray(np.abs(rng.randn(B, T, E)).astype(np.float32) + 0.1)

    def expert_weights(w_router):
        return {
            "w_router": jnp.asarray(w_router.astype(np.float32)),
            "w_gate": jnp.asarray(
                rng.randn(X, E, F).astype(np.float32) * 0.1),
            "w_up": jnp.asarray(
                rng.randn(X, E, F).astype(np.float32) * 0.1),
            "w_down": jnp.asarray(
                rng.randn(X, F, E).astype(np.float32) * 0.1),
        }

    balanced = expert_weights(rng.randn(E, X) * 0.02)
    _, aux_balanced, _ = tfm._moe_ffn(h, balanced, cfg, None)

    w_collapse = np.zeros((E, X))
    w_collapse[:, 0] = 10.0  # every (positive) token votes expert 0
    collapsed = expert_weights(w_collapse)
    _, aux_collapsed, _ = tfm._moe_ffn(h, collapsed, cfg, None)

    assert float(aux_balanced) < 1.5, float(aux_balanced)
    assert float(aux_collapsed) > 3.0, float(aux_collapsed)  # ~X=4


def test_moe_top2_uses_second_expert():
    """Top-2 combine must weight both chosen experts: zeroing the
    second-choice path changes the output (it didn't under top-1)."""
    cfg2 = tfm.TransformerConfig(
        vocab_size=128, dim=64, num_heads=4, num_layers=2,
        max_seq_len=32, dtype="float32", moe_experts=4, moe_top_k=2,
    )
    cfg1 = tfm.TransformerConfig(
        vocab_size=128, dim=64, num_heads=4, num_layers=2,
        max_seq_len=32, dtype="float32", moe_experts=4, moe_top_k=1,
    )
    params = tfm.init_params(jax.random.PRNGKey(5), cfg2)
    tokens = make_tokens(b=2)
    out2 = np.asarray(tfm.forward(params, tokens, cfg2))
    out1 = np.asarray(tfm.forward(params, tokens, cfg1))
    assert np.isfinite(out2).all()
    assert not np.allclose(out2, out1), (
        "top-2 output identical to top-1: second expert unused"
    )


def test_moe_aux_loss_trains_toward_balance_on_ep_mesh():
    """Training with the aux term on an ep mesh reduces router
    imbalance: expert-utilization spread shrinks vs the start."""
    mesh = build_mesh(dp=1, ep=2, tp=2, sp=2)
    cfg = tfm.TransformerConfig(
        vocab_size=128, dim=64, num_heads=4, num_layers=2,
        max_seq_len=32, dtype="float32", moe_experts=4, moe_top_k=2,
        moe_aux_weight=0.5,  # strong weight so few steps move it
    )

    def loss_fn(params, batch):
        tokens, _ = batch
        logits, aux = tfm.forward(params, tokens, cfg, mesh=mesh,
                                  return_aux=True)
        return (
            tfm.next_token_loss(logits, tokens).mean()
            + cfg.moe_aux_weight * aux
        )

    trainer = SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, cfg),
        loss_fn=loss_fn,
        optimizer=optax.adamw(5e-3),
        param_specs=tfm.param_specs(cfg),
        batch_spec=P("dp", "sp"),
    )
    tokens = make_tokens(b=4)
    aux_first = aux_last = None
    for step in range(6):
        # track the aux term itself: it must go down as balance improves
        _, aux = tfm.forward(
            jax.tree_util.tree_map(np.asarray, trainer.params),
            tokens, cfg, return_aux=True,
        )
        if aux_first is None:
            aux_first = float(aux)
        aux_last = float(aux)
        trainer.train_step((tokens, tokens))
    assert np.isfinite(aux_last)
    assert aux_last <= aux_first + 1e-3, (aux_first, aux_last)
