"""Flagship transformer: forward parity across parallelism layouts, and a
full 4-axis (dp/pp/tp/sp) train step on the virtual 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.models import transformer as tfm
from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SPMDTrainer

CFG = tfm.TransformerConfig(
    vocab_size=128, dim=64, num_heads=4, num_layers=2,
    max_seq_len=32, dtype="float32",
)


def make_tokens(b=4, t=32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, CFG.vocab_size, size=(b, t)).astype(np.int32)


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), CFG)


def test_forward_shapes_and_finite(params):
    tokens = make_tokens()
    logits = tfm.forward(params, tokens, CFG)
    assert logits.shape == (4, 32, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize(
    "axes", [dict(dp=2, tp=2, sp=2), dict(dp=1, tp=4, sp=2),
             dict(dp=8, tp=1, sp=1), dict(dp=1, pp=2, tp=2, sp=2)]
)
def test_sharded_forward_matches_single_device(params, axes):
    tokens = make_tokens()
    ref = np.asarray(tfm.forward(params, tokens, CFG))
    mesh = build_mesh(**axes)
    sharded = tfm.shard_params(params, mesh, CFG)
    out = jax.jit(
        lambda p, t: tfm.forward(p, t, CFG, mesh=mesh)
    )(sharded, tokens)
    np.testing.assert_allclose(ref, np.asarray(out), rtol=5e-4, atol=5e-4)


MOE_CFG = tfm.TransformerConfig(
    vocab_size=128, dim=64, num_heads=4, num_layers=2,
    max_seq_len=32, dtype="float32", moe_experts=4,
)


def test_moe_forward_matches_across_sharding():
    params = tfm.init_params(jax.random.PRNGKey(3), MOE_CFG)
    tokens = make_tokens(b=4)
    ref = np.asarray(tfm.forward(params, tokens, MOE_CFG))
    assert np.isfinite(ref).all()
    mesh = build_mesh(dp=1, ep=2, tp=2, sp=2)
    sharded = tfm.shard_params(params, mesh, MOE_CFG)
    out = jax.jit(
        lambda p, t: tfm.forward(p, t, MOE_CFG, mesh=mesh)
    )(sharded, tokens)
    np.testing.assert_allclose(ref, np.asarray(out), rtol=5e-4,
                               atol=5e-4)


def test_moe_ep_train_step_learns():
    mesh = build_mesh(dp=1, ep=2, tp=2, sp=2)

    def loss_fn(params, batch):
        tokens, _ = batch
        logits = tfm.forward(params, tokens, MOE_CFG, mesh=mesh)
        return tfm.next_token_loss(logits, tokens).mean()

    trainer = SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, MOE_CFG),
        loss_fn=loss_fn,
        optimizer=optax.adamw(2e-3),
        param_specs=tfm.param_specs(MOE_CFG),
        batch_spec=P("dp", "sp"),
    )
    tokens = make_tokens(b=4)
    losses = [float(trainer.train_step((tokens, tokens)))
              for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_full_4axis_train_step():
    mesh = build_mesh(dp=1, pp=2, tp=2, sp=2)

    def loss_fn(params, batch):
        tokens, _ = batch
        logits = tfm.forward(params, tokens, CFG, mesh=mesh)
        return tfm.next_token_loss(logits, tokens).mean()

    trainer = SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, CFG),
        loss_fn=loss_fn,
        optimizer=optax.adamw(1e-3),
        param_specs=tfm.param_specs(CFG),
        batch_spec=P("dp", "sp"),
    )
    tokens = make_tokens(b=4)
    losses = [float(trainer.train_step((tokens, tokens))) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], f"no learning: {losses}"


def test_loss_decreases_matches_unsharded_trajectory():
    """dp/tp/sp sharded training must follow the single-device trajectory."""
    tokens = make_tokens(b=4)
    tx = optax.sgd(0.1)

    def make_loss(mesh):
        def loss_fn(params, batch):
            toks, _ = batch
            logits = tfm.forward(params, toks, CFG, mesh=mesh)
            return tfm.next_token_loss(logits, toks).mean()
        return loss_fn

    # single device
    params = tfm.init_params(jax.random.PRNGKey(1), CFG)
    loss_single = make_loss(None)
    opt = tx.init(params)
    traj_single = []
    p = params
    for _ in range(3):
        l, g = jax.value_and_grad(loss_single)(p, (tokens, tokens))
        u, opt = tx.update(g, opt, p)
        p = optax.apply_updates(p, u)
        traj_single.append(float(l))

    mesh = build_mesh(dp=2, tp=2, sp=2)
    trainer = SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, CFG),
        loss_fn=make_loss(mesh),
        optimizer=tx,
        param_specs=tfm.param_specs(CFG),
        batch_spec=P("dp", "sp"),
        rng_seed=1,
    )
    traj_sharded = [
        float(trainer.train_step((tokens, tokens))) for _ in range(3)
    ]
    np.testing.assert_allclose(traj_single, traj_sharded, rtol=2e-3)
