"""Checkpoint resume: the master skips already-trained records."""

from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elastic_pb2 as pb


def test_skip_records_drops_whole_and_partial_tasks():
    tm = TaskManager(training_shards=[("f", 0, 100)], records_per_task=30)
    skipped = tm.skip_records(45)  # task1 (30) + 15 of task2
    assert skipped == 45
    t = tm.get(0)
    assert (t.shard.start, t.shard.end) == (45, 60)
    remaining = t.shard.size
    while True:
        tm.report(t.id, True)
        t = tm.get(0)
        if t is None:
            break
        remaining += t.shard.size
    assert remaining == 55
    assert tm.completed_counts[pb.TRAINING] >= 1  # skipped task counted


def test_skip_records_beyond_epoch_is_bounded():
    tm = TaskManager(training_shards=[("f", 0, 50)], records_per_task=25)
    assert tm.skip_records(10_000) == 50
    assert tm.get(0) is None or True  # next epoch logic unaffected
