"""Binary zero-copy serving data plane (docs/serving.md "Wire
protocol"): content-type negotiation on the model server, JSON-vs-
binary response bit-identity under concurrency, per-request bf16
opt-in, loud refusal of malformed frames, and the router's
pass-through invariants — keyed placement off the frame HEADER only,
forwarded bodies byte-identical, content type preserved, zero
re-encode."""

import json
import threading
import time
import http.client
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from elasticdl_tpu.serving.batcher import BatchConfig
from elasticdl_tpu.serving.export import export_servable
from elasticdl_tpu.serving.server import ModelEndpoint, build_server
from elasticdl_tpu.utils import tensor_codec as tc

W = np.arange(8, dtype=np.float32).reshape(4, 2)
EMB = (np.array([5, 9]),
       np.arange(8, dtype=np.float32).reshape(2, 4))


@pytest.fixture(scope="module")
def export_dir(tmp_path_factory):
    path = tmp_path_factory.mktemp("exports") / "lin"
    export_servable(
        str(path), lambda p, x: x @ p["w"], {"w": W},
        np.zeros((1, 4), np.float32), model_name="lin", version=3,
        embeddings={"users": EMB}, platforms=("cpu",),
    )
    return str(path)


@pytest.fixture(scope="module")
def served(export_dir):
    endpoint = ModelEndpoint(
        export_dir,
        batching=BatchConfig(max_batch_size=8, batch_timeout_ms=5.0,
                             warm=False))
    server = build_server(endpoint, port=0)
    thread = threading.Thread(target=server.serve_forever,
                              daemon=True)
    thread.start()
    yield endpoint, server.server_address[1]
    server.shutdown()
    server.server_close()
    endpoint.close()


def _post(port, path, body, content_type=None, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        headers = {}
        if content_type:
            headers["Content-Type"] = content_type
        conn.request("POST", path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read(), resp.getheader("Content-Type")
    finally:
        conn.close()


def _predict_binary(port, x, meta=None):
    blob = tc.encode_frame({"instances": x}, kind="predict",
                           meta=meta)
    return _post(port, "/v1/models/lin:predict", blob,
                 tc.FRAME_CONTENT_TYPE)


def test_json_and_binary_responses_bit_identical(served):
    _, port = served
    x = np.random.RandomState(1).randn(4, 4).astype(np.float32)
    status, raw, _ = _post(port, "/v1/models/lin:predict",
                           json.dumps({"instances": x.tolist()}))
    assert status == 200
    jout = json.loads(raw)
    status, raw, ctype = _predict_binary(port, x)
    assert status == 200
    assert ctype == tc.FRAME_CONTENT_TYPE
    frame = tc.decode_frame(raw)
    preds = tc.unflatten_tree(frame.meta["tree"], frame.tensors)
    assert preds.dtype == np.float32
    # Bit-identical to the JSON fallback on the same model.
    assert np.array_equal(
        preds, np.asarray(jout["predictions"], np.float32))
    assert frame.model_version == jout["model_version"] == 3


def test_bit_identity_under_concurrency(served):
    """8 client threads mixing both content types against the SAME
    batcher admission queue: every binary response must equal the JSON
    response for the same row, and version stamps never diverge —
    coalescing is content-type-blind."""
    _, port = served
    rng = np.random.RandomState(7)
    rows = rng.randn(8, 4).astype(np.float32)
    errors = []
    barrier = threading.Barrier(8)

    def client(idx):
        x = rows[idx:idx + 1]
        raw_json = json.dumps({"instances": x.tolist()})
        blob = tc.encode_frame({"instances": x}, kind="predict")
        try:
            barrier.wait(timeout=30)
            for _ in range(10):
                s1, r1, _ = _post(port, "/v1/models/lin:predict",
                                  raw_json)
                s2, r2, _ = _post(port, "/v1/models/lin:predict",
                                  blob, tc.FRAME_CONTENT_TYPE)
                assert s1 == 200 and s2 == 200
                jout = json.loads(r1)
                frame = tc.decode_frame(r2)
                preds = tc.unflatten_tree(frame.meta["tree"],
                                          frame.tensors)
                if not np.array_equal(
                        preds,
                        np.asarray(jout["predictions"], np.float32)):
                    errors.append("row %d mismatch" % idx)
                if frame.model_version != jout["model_version"]:
                    errors.append("version mismatch")
        except Exception as e:  # noqa: BLE001 — surface, don't hang
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:3]


def test_bf16_response_opt_in(served):
    ml_dtypes = pytest.importorskip("ml_dtypes")
    _, port = served
    x = np.random.RandomState(2).randn(2, 4).astype(np.float32)
    status, raw, _ = _predict_binary(port, x)
    full = tc.unflatten_tree(*_frame_parts(raw))
    status, raw, _ = _predict_binary(
        port, x, meta={"response_wire": "bfloat16"})
    assert status == 200
    compressed = tc.unflatten_tree(*_frame_parts(raw))
    assert compressed.dtype == np.float32
    assert np.array_equal(
        compressed,
        full.astype(ml_dtypes.bfloat16).astype(np.float32))
    # An unknown wire dtype is a client error, not a silent full-
    # precision reply.
    status, raw, _ = _predict_binary(
        port, x, meta={"response_wire": "float8"})
    assert status == 400
    assert "response_wire" in json.loads(raw)["error"]


def _frame_parts(raw):
    frame = tc.decode_frame(raw)
    return frame.meta["tree"], frame.tensors


def test_binary_lookup_matches_json(served):
    _, port = served
    ids = [5, 1, 9, 5]
    status, raw, _ = _post(port, "/v1/models/lin:lookup",
                           json.dumps({"table": "users", "ids": ids}))
    assert status == 200
    jout = json.loads(raw)
    blob = tc.encode_frame({"ids": np.asarray(ids, np.int64)},
                           kind="lookup", meta={"table": "users"})
    status, raw, _ = _post(port, "/v1/models/lin:lookup", blob,
                           tc.FRAME_CONTENT_TYPE)
    assert status == 200
    frame = tc.decode_frame(raw)
    assert frame.meta["source"] == "export"
    assert np.array_equal(frame.tensors["vectors"],
                          np.asarray(jout["vectors"], np.float32))
    # Missing table meta is a 400, not a KeyError 500.
    blob = tc.encode_frame({"ids": np.asarray(ids, np.int64)},
                           kind="lookup")
    status, raw, _ = _post(port, "/v1/models/lin:lookup", blob,
                           tc.FRAME_CONTENT_TYPE)
    assert status == 400


def test_malformed_frames_refused_loudly(served):
    _, port = served
    for body in (b"", b"shrt", b"NOPE" + b"\x00" * 32,
                 tc.encode_frame({"x": np.zeros(4)})[:-3]):
        status, raw, _ = _post(port, "/v1/models/lin:predict", body,
                               tc.FRAME_CONTENT_TYPE)
        assert status == 400
        assert "bad frame" in json.loads(raw)["error"]
    # The server survives garbage: a good request still works.
    x = np.zeros((1, 4), np.float32)
    status, _, _ = _predict_binary(port, x)
    assert status == 200


def test_request_histogram_on_statz_and_metrics(served):
    endpoint, port = served
    _predict_binary(port, np.zeros((1, 4), np.float32))
    stats = endpoint.stats()
    hist = stats["hists"].get("serving.request")
    assert hist and hist["count"] >= 1
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", "/metrics")
    metrics = conn.getresponse().read().decode()
    conn.close()
    assert "elasticdl_serving_request_seconds_bucket" in metrics


# -- router pass-through invariants ---------------------------------------


class _CapturingReplica:
    """A fake model server that records exactly what the router sent
    and answers with a distinctive binary body."""

    def __init__(self):
        self.captured = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_GET(self):
                body = json.dumps(
                    {"draining": False, "models": {}}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n)
                outer.captured.append(
                    (self.path, self.headers.get("Content-Type"),
                     raw))
                body = b"\x01\x02frame-reply"
                self.send_response(200)
                self.send_header("Content-Type",
                                 tc.FRAME_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.addr = "127.0.0.1:%d" % self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


@pytest.fixture()
def routed():
    from elasticdl_tpu.serving.router import (
        Router,
        build_router_server,
    )

    replicas = [_CapturingReplica(), _CapturingReplica()]
    router = Router([r.addr for r in replicas], probe_interval=0.1)
    router.start()
    server = build_router_server(router, port=0)
    threading.Thread(target=server.serve_forever,
                     daemon=True).start()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if len(router.state.routable(None)) == 2:
            break
        time.sleep(0.05)
    else:
        raise RuntimeError("replicas never became routable")
    yield router, server.server_address[1], replicas
    router.stop()
    server.shutdown()
    server.server_close()
    for r in replicas:
        r.close()


def test_router_forwards_binary_bodies_byte_identical(routed):
    router, port, replicas = routed
    from elasticdl_tpu.serving.fleet import pick_replica

    blob = tc.encode_frame(
        {"instances": np.random.RandomState(0)
         .randn(16, 4).astype(np.float32)},
        kind="predict", routing_key="user-42")
    status, raw, ctype = _post(port, "/v1/models/lin:predict", blob,
                               tc.FRAME_CONTENT_TYPE)
    assert status == 200
    # Response bytes AND content type pass through untouched.
    assert raw == b"\x01\x02frame-reply"
    assert ctype == tc.FRAME_CONTENT_TYPE
    sent = [r for r in replicas if r.captured]
    assert len(sent) == 1
    path, fwd_type, fwd_raw = sent[0].captured[-1]
    # Byte-identical forward: zero re-encode, content type preserved.
    assert fwd_raw == blob
    assert fwd_type == tc.FRAME_CONTENT_TYPE
    # The frame header's routing key drove HRW placement: the chosen
    # replica is exactly the rendezvous pick for this key.
    expected = pick_replica("user-42",
                            sorted(r.addr for r in replicas))
    assert sent[0].addr == expected
    # Same key -> same replica, every time (header-only read is
    # deterministic).
    for _ in range(3):
        _post(port, "/v1/models/lin:predict", blob,
              tc.FRAME_CONTENT_TYPE)
    assert {r.addr for r in replicas if r.captured} == {expected}


def test_router_x_routing_key_skips_body_inspection(routed):
    _, port, replicas = routed
    # The body is NOT valid JSON and NOT a frame — with an explicit
    # header key the router must not even try to parse it.
    body = b"\x00\xffnot-json-not-frame"
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("POST", "/v1/models/lin:predict", body=body,
                     headers={"X-Routing-Key": "k7",
                              "Content-Type":
                                  "application/octet-stream"})
        resp = conn.getresponse()
        assert resp.status == 200
        resp.read()
    finally:
        conn.close()
    sent = [r for r in replicas if r.captured]
    assert sent and sent[0].captured[-1][2] == body
    assert sent[0].captured[-1][1] == "application/octet-stream"


def test_router_refuses_malformed_frame_without_forwarding(routed):
    _, port, replicas = routed
    before = sum(len(r.captured) for r in replicas)
    status, raw, _ = _post(port, "/v1/models/lin:predict",
                           b"EDXXgarbage-garbage-garbage",
                           tc.FRAME_CONTENT_TYPE)
    assert status == 400
    assert "bad frame" in json.loads(raw)["error"]
    assert sum(len(r.captured) for r in replicas) == before
    # A frame whose preamble LIES about its size must be refused from
    # the header read alone (never forwarded, never hangs).
    blob = bytearray(tc.encode_frame({"x": np.zeros(2, np.float32)},
                                     routing_key="k"))
    blob[8:16] = (99999).to_bytes(8, "little")  # payload_len lie
    status, raw, _ = _post(port, "/v1/models/lin:predict",
                           bytes(blob), tc.FRAME_CONTENT_TYPE)
    assert status == 400
    assert sum(len(r.captured) for r in replicas) == before


def test_router_binary_lookup_gets_table_affinity_key(routed):
    """A binary :lookup without an explicit routing key derives the
    SAME "table:<name>" affinity key the JSON path uses — one table's
    hot rows stay in ONE replica's cache regardless of content
    type."""
    from elasticdl_tpu.serving.fleet import pick_replica

    blob = tc.encode_frame({"ids": np.arange(4, dtype=np.int64)},
                           kind="lookup", meta={"table": "users"})
    status, _, _ = _post(routed[1], "/v1/models/lin:lookup", blob,
                         tc.FRAME_CONTENT_TYPE)
    assert status == 200
    replicas = routed[2]
    sent = [r for r in replicas if r.captured]
    assert len(sent) == 1
    expected = pick_replica("table:users",
                            sorted(r.addr for r in replicas))
    assert sent[0].addr == expected
    # JSON lookups for the same table land on the SAME replica.
    status, _, _ = _post(routed[1], "/v1/models/lin:lookup",
                         json.dumps({"table": "users",
                                     "ids": [1, 2]}))
    assert status == 200
    assert {r.addr for r in replicas if r.captured} == {expected}
