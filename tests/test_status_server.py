"""Master HTTP observability surface (master/status_server.py):
/healthz, /status JSON, /metrics Prometheus text."""

import json
import urllib.request

from elasticdl_tpu.master.status_server import (
    StatusServer,
    to_prometheus,
)
from elasticdl_tpu.proto import elastic_pb2 as pb
from tests.test_utils import create_master, create_master_client


def _get(port, path):
    with urllib.request.urlopen(
        "http://127.0.0.1:%d%s" % (port, path), timeout=10
    ) as resp:
        return resp.status, resp.read().decode()


def test_status_endpoints_reflect_job_state():
    master = create_master(
        training_shards=[("f", 0, 64)], records_per_task=16,
        rendezvous=True,
    )
    server = StatusServer(
        master.task_manager,
        rendezvous_server=master.rendezvous_server,
        servicer=master.servicer,
        host="127.0.0.1",
    )
    server.start()
    try:
        code, body = _get(server.port, "/healthz")
        assert (code, body) == (200, "ok\n")

        mc = create_master_client(master, worker_id=0)
        mc.report_train_loop_status(pb.LOOP_START)
        task = mc.get_task()
        mc.report_task_result(task.id)  # one task completed

        code, body = _get(server.port, "/status")
        assert code == 200
        status = json.loads(body)
        assert status["tasks"]["completed"][str(pb.TRAINING)] == 1
        assert status["tasks"]["todo"] == 3
        assert status["finished"] is False
        assert status["rendezvous"]["world"] in ([], ["worker-0"])

        code, text = _get(server.port, "/metrics")
        assert code == 200
        metrics = dict(
            line.rsplit(" ", 1) for line in text.strip().splitlines()
        )
        assert metrics["elasticdl_tasks_todo"] == "3"
        assert metrics['elasticdl_tasks_completed{type="0"}'] == "1"
        assert metrics["elasticdl_job_finished"] == "0"

        code, _ = _get(server.port, "/nope")
        assert code == 404
    except urllib.error.HTTPError as e:
        if e.code != 404:
            raise
    finally:
        server.stop()
        master.stop()


def test_prometheus_rendering_shapes():
    status = {
        "tasks": {"todo": 2, "doing": 1, "epoch": 0,
                  "completed": {0: 5}, "failed": {0: 0}},
        "finished": False,
        "workers": {"live": [0, 2]},
        "rendezvous": {"epoch": 3, "world": ["a", "b"]},
        "exec_counters": {"batch_count": 17},
    }
    text = to_prometheus(status)
    assert 'elasticdl_tasks_completed{type="0"} 5' in text
    assert "elasticdl_workers_live 2" in text
    assert "elasticdl_rendezvous_world_size 2" in text
    assert 'elasticdl_worker_counter{name="batch_count"} 17' in text


def test_ps_status_endpoint(tmp_path):
    """The PS shard's observability twin: counters + version over the
    shared HttpStatusServer."""
    import numpy as np

    from elasticdl_tpu.ps.server import ParameterServer
    from elasticdl_tpu.utils.args import parse_ps_args
    from elasticdl_tpu.utils import grpc_utils
    from elasticdl_tpu.worker.ps_client import PSClient

    ps = ParameterServer(parse_ps_args(
        ["--port", "0", "--status_port", "0",
         "--opt_args", "learning_rate=0.1"]))
    ps.prepare()
    try:
        channel = grpc_utils.build_channel("localhost:%d" % ps.port)
        grpc_utils.wait_for_channel_ready(channel)
        client = PSClient([channel])
        client.push_model({"w": np.ones(3, np.float32)})
        client.push_gradients({"w": np.ones(3, np.float32)}, {},
                              version=0)
        client.pull_dense_parameters(-1)

        code, body = _get(ps._status_server.port, "/status")
        assert code == 200
        status = json.loads(body)
        assert status["version"] == 1
        assert status["counters"]["push_accepted"] == 1
        assert status["counters"]["pull_dense"] >= 1

        code, text = _get(ps._status_server.port, "/metrics")
        assert code == 200
        assert "elasticdl_ps_version 1" in text
        assert 'elasticdl_ps_requests{kind="push_accepted"} 1' in text
    finally:
        ps.stop()
