"""Worker <-> PS integration: DeepFM trains through real gRPC PS shards
(reference pattern: worker_ps_interaction_test.py:203-356 incl. the PS
restart fault-tolerance test)."""

import numpy as np
import pytest

from elasticdl_tpu.models import deepfm
from elasticdl_tpu.utils import metrics
from elasticdl_tpu.worker.ps_trainer import (
    GradientsRejected,
    ParameterServerTrainer,
)
from tests.test_pserver import start_ps, stop_all

VOCAB = 1000


@pytest.fixture(scope="module")
def dataset():
    return deepfm.synthetic_data(n=512, vocab_size=VOCAB, seed=3)


def batches(dataset, spec, batch_size=64):
    dense, ids, labels = dataset
    out = []
    for i in range(0, len(labels), batch_size):
        records = [
            (dense[j], ids[j], labels[j])
            for j in range(i, min(i + batch_size, len(labels)))
        ]
        out.append(spec.feed(records))
    return out

def test_deepfm_trains_through_ps(dataset):
    spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                             hidden=(32,))
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="adam", opt_args="learning_rate=0.01",
        use_async=True,
    )
    try:
        trainer = ParameterServerTrainer(
            spec, client, batch_size=64, get_model_steps=1
        )
        data = batches(dataset, spec)
        first_loss = None
        for epoch in range(6):
            for features, labels in data:
                loss, version = trainer.train_minibatch(features, labels)
                if first_loss is None:
                    first_loss = loss
        assert version > 0
        assert loss < first_loss, (first_loss, loss)

        auc = metrics.AUC()
        for features, labels in data:
            outputs, labels = trainer.evaluate_minibatch(features, labels)
            auc.update(1 / (1 + np.exp(-outputs)), labels)
        assert auc.result() > 0.75, auc.result()
    finally:
        stop_all(servers)


def test_sync_mode_rejection_retry_path(dataset):
    """Two trainers against a sync PS with zero tolerance: a stale push
    raises GradientsRejected and succeeds after re-pull."""
    spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                             hidden=(16,))
    client, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=0.01",
        use_async=False, grads_to_wait=1, sync_version_tolerance=0,
    )
    try:
        t1 = ParameterServerTrainer(spec, client, batch_size=64)
        # t2 pulls only on its first step, so later steps can go stale
        t2 = ParameterServerTrainer(spec, client, batch_size=64,
                                    get_model_steps=100)
        data = batches(dataset, spec)
        t1.train_minibatch(*data[0])          # server -> version 1
        t2.train_minibatch(*data[1])          # pulls v1, server -> v2
        t1.train_minibatch(*data[2])          # pulls v2, server -> v3
        with pytest.raises(GradientsRejected):
            t2.train_minibatch(*data[3])      # pushes at v2 < v3: stale
        # the raise triggered a re-pull; retry succeeds
        loss, version = t2.train_minibatch(*data[3])
        assert version == 4
    finally:
        stop_all(servers)


def test_ps_restart_reinitialized_by_worker(dataset):
    """Kill the PS mid-training; a fresh PS gets re-initialized by the
    worker's push-to-init (reference test_restart_ps semantics)."""
    spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                             hidden=(16,))
    client, servicers, servers = start_ps(num_ps=1)
    data = batches(dataset, spec)
    trainer = ParameterServerTrainer(spec, client, batch_size=64)
    trainer.train_minibatch(*data[0])
    stop_all(servers)

    # fresh PS on a new port; trainer gets a fresh client
    client2, servicers2, servers2 = start_ps(num_ps=1)
    try:
        trainer._ps = client2
        # the next pull detects the uninitialized PS and re-pushes the
        # local model automatically — training continues without manual
        # intervention
        loss, version = trainer.train_minibatch(*data[1])
        assert np.isfinite(loss)
        assert servicers2[0]._params.initialized
    finally:
        stop_all(servers2)
