"""Overlapped PS hot path: bf16 wire compression, pipelined async push,
embedding-pull prefetch, and the empty-ids shape fix.

In-process gRPC PS shards (same rig as test_pserver) so every assertion
runs against the real codec + servicer + optimizer stack."""

import numpy as np
import pytest

from elasticdl_tpu.models import deepfm
from elasticdl_tpu.proto import rpc
from elasticdl_tpu.ps.optimizer import create_optimizer
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.worker.ps_client import PSClient
from elasticdl_tpu.worker.ps_trainer import (
    GradientsRejected,
    ParameterServerTrainer,
)

VOCAB = 500


def start_ps(num_ps=1, opt_type="sgd", opt_args="learning_rate=0.1",
             **kwargs):
    """Boot N in-process PS shards; returns (addrs, servicers, servers)
    — addrs (not a client) so tests can build clients with any
    wire_dtype / push-channel configuration."""
    servers, servicers, addrs = [], [], []
    for i in range(num_ps):
        servicer = PserverServicer(
            Parameters(),
            create_optimizer(opt_type, opt_args),
            ps_id=i, num_ps=num_ps, **kwargs,
        )
        server = grpc_utils.build_server(max_workers=8)
        rpc.add_pserver_servicer(servicer, server)
        port = server.add_insecure_port("[::]:0")
        server.start()
        servers.append(server)
        servicers.append(servicer)
        addrs.append("localhost:%d" % port)
    return addrs, servicers, servers


def make_client(addrs, wire_dtype=None, dedicated_push_channels=False):
    def connect():
        channels = []
        for addr in addrs:
            ch = grpc_utils.build_channel(addr)
            grpc_utils.wait_for_channel_ready(ch)
            channels.append(ch)
        return channels

    return PSClient(
        connect(), wire_dtype=wire_dtype,
        push_channels=connect() if dedicated_push_channels else None,
    )


def stop_all(servers):
    for s in servers:
        s.stop(grace=None)


def batches(spec, n=256, batch_size=64, seed=3):
    dense, ids, labels = deepfm.synthetic_data(
        n=n, vocab_size=VOCAB, seed=seed
    )
    out = []
    for i in range(0, len(labels), batch_size):
        records = [
            (dense[j], ids[j], labels[j])
            for j in range(i, min(i + batch_size, len(labels)))
        ]
        out.append(spec.feed(records))
    return out


# -- bf16 wire ----------------------------------------------------------


def test_bf16_push_accumulates_f32_on_ps():
    """A bf16-wire gradient push must land on f32 master copies with
    only bf16 quantization error — never bf16 accumulation."""
    addrs, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=1.0"
    )
    try:
        client = make_client(addrs, wire_dtype="bfloat16")
        rng = np.random.default_rng(0)
        dense = {"w": rng.standard_normal(64).astype(np.float32)}
        client.push_model(dense)
        grad = rng.standard_normal(64).astype(np.float32) * 1e-3
        accepted, _ = client.push_gradients({"w": grad})
        assert accepted
        param = servicers[0]._params.dense["w"]
        assert param.dtype == np.float32
        # lr=1.0: param == init - bf16(grad); bf16 has ~3 decimal
        # digits, grads are ~1e-3, so error <= ~1e-5 per element.
        np.testing.assert_allclose(
            param, dense["w"] - grad, atol=2e-5
        )
        # and the tiny update must not be lost entirely
        assert np.abs(param - dense["w"]).max() > 1e-5
    finally:
        stop_all(servers)


def test_pull_embedding_bf16_wire_matches_f32():
    addrs, servicers, servers = start_ps(num_ps=2)
    try:
        f32 = make_client(addrs)
        bf16 = make_client(addrs, wire_dtype="bfloat16")
        infos = [{"name": "t", "dim": 8, "initializer": "uniform"}]
        f32.push_model({"w": np.zeros(2, np.float32)},
                       embedding_infos=infos)
        ids = np.array([3, 11, 7, 3], np.int64)
        exact = f32.pull_embedding_vectors("t", ids)
        approx = bf16.pull_embedding_vectors("t", ids)
        assert exact.dtype == approx.dtype == np.float32
        assert exact.shape == approx.shape == (4, 8)
        # init rows are U(-0.05, 0.05): bf16 relative error ~2^-8
        np.testing.assert_allclose(exact, approx, atol=4e-4)
        assert np.array_equal(approx[0], approx[3])  # same id, same row
    finally:
        stop_all(servers)


def test_bad_wire_dtype_rejected():
    with pytest.raises(ValueError):
        PSClient([], wire_dtype="float8")


# -- empty-ids pull shape -----------------------------------------------


def test_empty_ids_pull_keeps_dim():
    addrs, servicers, servers = start_ps(num_ps=2)
    try:
        client = make_client(addrs)
        # explicit dim wins even before any infos are known
        assert client.pull_embedding_vectors("t", [], dim=6).shape == (0, 6)
        infos = [{"name": "t", "dim": 8, "initializer": "zeros"}]
        client.push_embedding_table_infos(infos)
        out = client.pull_embedding_vectors("t", [])
        assert out.shape == (0, 8)
        assert out.dtype == np.float32
    finally:
        stop_all(servers)


def test_parameters_empty_ids_pull_keeps_dim():
    params = Parameters()
    params.set_embedding_infos(
        [{"name": "t", "dim": 5, "initializer": "zeros"}]
    )
    out = params.pull_embedding_vectors("t", np.zeros((0,), np.int64))
    assert out.shape == (0, 5)


# -- pipelined push -----------------------------------------------------


def test_pipelined_stale_reject_drains_and_recovers():
    """Forced stale reject: the pipelined trainer surfaces
    GradientsRejected on a LATER minibatch, with the pipeline drained
    and dense params re-pulled, and the retry then converges with the
    server version."""
    spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                             hidden=(16,))
    addrs, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=0.01",
        use_async=False, grads_to_wait=1, sync_version_tolerance=0,
    )
    try:
        t1 = ParameterServerTrainer(
            spec, make_client(addrs), batch_size=64
        )
        t2 = ParameterServerTrainer(
            spec, make_client(addrs, dedicated_push_channels=True),
            batch_size=64, get_model_steps=100, async_push_window=1,
        )
        data = batches(spec)
        t2.train_minibatch(*data[0])       # push P1 in flight @v0
        t2.drain_pushes()                  # P1 accepted -> server v1
        t1.train_minibatch(*data[1])       # t1 pulls v1, push -> v2
        t2.train_minibatch(*data[2])       # P2 submitted @stale v0
        with pytest.raises(GradientsRejected):
            # draining P2 at the next submit surfaces the reject
            t2.train_minibatch(*data[3])
        assert not t2._push_inflight       # pipeline drained
        assert t2.version == servicers[0]._params.version  # re-pulled
        assert servicers[0].counters["push_rejected"] >= 1
        # the worker's retry path: same minibatch goes through now
        t2.train_minibatch(*data[3])
        t2.drain_pushes()
        assert servicers[0]._params.version == t2.version + 1
        t1.close()
        t2.close()
    finally:
        stop_all(servers)


def test_pipelined_matches_serialized_exactly_when_draining_each_pull():
    """window=1 with a dense pull every step drains the pipeline every
    step: the push merely moves to the next step's start, so the update
    sequence on the PS — and the converged dense params — are
    IDENTICAL to the serialized loop."""
    results = []
    for window in (0, 1):
        spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                                 hidden=(16,))
        addrs, _servicers, servers = start_ps(
            num_ps=2, opt_type="sgd", opt_args="learning_rate=0.01",
            use_async=True,
        )
        try:
            trainer = ParameterServerTrainer(
                spec,
                make_client(addrs, dedicated_push_channels=window > 0),
                batch_size=64, get_model_steps=1, rng_seed=7,
                async_push_window=window,
            )
            data = batches(spec, n=320)
            losses = []
            for step in range(50):
                loss, _ = trainer.train_minibatch(
                    *data[step % len(data)]
                )
                losses.append(loss)
            trainer.drain_pushes()
            client = make_client(addrs)
            _, version, dense = client.pull_dense_parameters(-1)
            results.append((losses, version, dense))
            trainer.close()
        finally:
            stop_all(servers)
    (loss_a, ver_a, dense_a), (loss_b, ver_b, dense_b) = results
    assert ver_a == ver_b
    np.testing.assert_allclose(loss_a, loss_b, rtol=1e-6)
    assert set(dense_a) == set(dense_b)
    for name in dense_a:
        np.testing.assert_allclose(
            dense_a[name], dense_b[name], rtol=1e-6, atol=1e-7,
            err_msg=name,
        )


def test_full_pipeline_converges_close_to_serialized():
    """The full overlapped path (window 1 + prefetch + pull cadence 5 +
    bf16 wire) trains to the same place within bounded-staleness +
    quantization tolerance on a fixed-seed 50-step run."""
    results = []
    for pipelined in (False, True):
        spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                                 hidden=(16,))
        addrs, _servicers, servers = start_ps(
            num_ps=2, opt_type="sgd", opt_args="learning_rate=0.01",
            use_async=True,
        )
        try:
            trainer = ParameterServerTrainer(
                spec,
                make_client(
                    addrs,
                    wire_dtype="bfloat16" if pipelined else None,
                    dedicated_push_channels=pipelined,
                ),
                batch_size=64, get_model_steps=5, rng_seed=7,
                async_push_window=1 if pipelined else 0,
            )
            data = batches(spec, n=320)
            first = last = None
            for step in range(50):
                if pipelined:
                    trainer.prefetch_embeddings(
                        data[(step + 1) % len(data)][0]
                    )
                last, _ = trainer.train_minibatch(
                    *data[step % len(data)]
                )
                if first is None:
                    first = last
            trainer.drain_pushes()
            client = make_client(addrs)
            _, _, dense = client.pull_dense_parameters(-1)
            results.append((first, last, dense))
            if pipelined:
                hits = trainer.timing.counters().get("prefetch_hit", 0)
                assert hits > 0  # the prefetcher actually served pulls
            trainer.close()
        finally:
            stop_all(servers)
    (first_a, last_a, dense_a), (first_b, last_b, dense_b) = results
    assert last_a < first_a and last_b < first_b  # both trained
    for name in dense_a:
        np.testing.assert_allclose(
            dense_a[name], dense_b[name], atol=5e-2, err_msg=name,
        )


def test_atomic_sync_ignores_push_window():
    """Sync 2PC jobs stay strictly ordered: the window is overridden to
    0 and every push is the blocking prepare/commit, exactly as before
    the pipeline existed."""
    spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                             hidden=(16,))
    addrs, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=0.01",
        use_async=False, grads_to_wait=1,
    )
    try:
        trainer = ParameterServerTrainer(
            spec, make_client(addrs), batch_size=64,
            atomic_sync=True, async_push_window=4,
        )
        assert trainer._push_window == 0
        before = [s._params.version for s in servicers]
        trainer.train_minibatch(*batches(spec)[0])
        assert not trainer._push_inflight
        # blocking 2PC: both shards applied before train_minibatch
        # returned
        for s, v in zip(servicers, before):
            assert s._params.version == v + 1
        trainer.close()
    finally:
        stop_all(servers)


def test_prefetch_rows_match_direct_pull():
    """Two identical trainers on two identical PS setups (table init is
    seeded by table name, so separate instances start bit-identical):
    the prefetched step must produce exactly the direct step's loss."""
    spec = deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                             hidden=(16,))
    losses = []
    for use_prefetch in (False, True):
        addrs, _servicers, servers = start_ps(num_ps=2)
        try:
            trainer = ParameterServerTrainer(
                spec,
                make_client(addrs, dedicated_push_channels=use_prefetch),
                batch_size=64, rng_seed=5,
                # prefetch is a pipelined-mode feature; outside it the
                # call must be a no-op (ordering guarantee)
                async_push_window=1 if use_prefetch else 0,
            )
            feats, labels = batches(spec)[0]
            trainer.prefetch_embeddings(feats)
            counters = trainer.timing.counters()
            if not use_prefetch:
                assert not trainer._prefetched  # no-op outside pipeline
            loss, _ = trainer.train_minibatch(feats, labels)
            losses.append(loss)
            if use_prefetch:
                counters = trainer.timing.counters()
                assert counters.get("prefetch_hit") == 2  # both tables
                assert not counters.get("prefetch_miss")
            trainer.close()
        finally:
            stop_all(servers)
    np.testing.assert_allclose(losses[1], losses[0], rtol=1e-6)
