"""DataShardService record accounting (ADVICE r1: report_task_failed wiped
progress belonging to other pending tasks)."""

from types import SimpleNamespace

from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.worker.data_shard_service import DataShardService


class FakeMasterClient:
    def __init__(self, sizes):
        self._tasks = [
            SimpleNamespace(
                id=i, type=pb.TRAINING,
                shard=SimpleNamespace(name="s", start=0, end=size,
                                      record_indices=[]),
                model_version=-1,
            )
            for i, size in enumerate(sizes)
        ]
        self.results = []  # (task_id, err_message)

    def get_task(self, task_type=None):
        if self._tasks:
            return self._tasks.pop(0)
        return SimpleNamespace(id=-1, type=pb.NONE, shard=None,
                               model_version=-1)

    def report_batch_done(self, count, telemetry=None):
        pass

    def report_task_result(self, task_id, err_message="",
                           exec_counters=None, requeue=False):
        self.results.append((task_id, err_message))


def test_failed_head_drops_only_its_own_records():
    mc = FakeMasterClient([10, 10])
    svc = DataShardService(mc, batch_size=5)
    t0 = svc.fetch_task()
    t1 = svc.fetch_task()
    svc.report_batch_done(5)            # 5 records into t0
    svc.report_task_failed(t0, "boom")  # head fails
    assert svc._record_count == 0
    svc.report_batch_done(5)
    svc.report_batch_done(5)            # t1's 10 records complete it
    assert (t1.id, "") in mc.results


def test_failed_non_head_preserves_head_progress():
    mc = FakeMasterClient([10, 10])
    svc = DataShardService(mc, batch_size=5)
    t0 = svc.fetch_task()
    t1 = svc.fetch_task()
    svc.report_batch_done(5)            # 5 records counted toward t0 (head)
    svc.report_task_failed(t1, "boom")  # NOT the head
    assert svc._record_count == 5       # t0's progress survives
    svc.report_batch_done(5)            # t0 completes
    assert (t0.id, "") in mc.results
