"""The reference's PyTorch path: a stock torch loop made elastic."""

import time

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from elasticdl_tpu.models import mnist_torch  # noqa: E402
from tests.test_utils import create_master, create_master_client  # noqa: E402


def test_torch_elastic_loop_completes_and_learns():
    master = create_master(
        training_shards=[("mem", 0, 512)], records_per_task=64,
        rendezvous=True,
    )
    try:
        mc = create_master_client(master)
        time.sleep(0.15)  # rendezvous grace
        loss, batches = mnist_torch.train(mc, n_records=512,
                                          batch_size=32)
        assert batches == 16
        assert np.isfinite(loss)
        assert master.task_manager.finished()
    finally:
        master.stop()
