"""PS-shard lifecycle drills: launch real PS subprocesses, SIGKILL one,
assert relaunch-with-restore serves consistent state (the PS half of the
elasticity story; reference: PS pods protected by priority + relaunch,
pod_manager.py:173-177, checkpoint restore go/pkg/ps/checkpoint.go)."""

import os
import signal
import time

import numpy as np
import pytest

from elasticdl_tpu.master.ps_manager import PSManager
from elasticdl_tpu.worker.ps_client import build_ps_client
from tests.util import wait_until


def make_client(manager):
    return build_ps_client(manager.addrs)


@pytest.mark.slow
def test_ps_shard_sigkill_relaunches_with_restored_state(tmp_path):
    manager = PSManager(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        checkpoint_dir=str(tmp_path), checkpoint_steps=1,
    )
    manager.start()
    try:
        client = make_client(manager)
        client.push_model(
            {"a": np.zeros(4, np.float32), "b": np.zeros(4, np.float32)},
            embedding_infos=[
                {"name": "emb", "dim": 4, "initializer": "zeros"}
            ],
        )
        ids = np.arange(8, dtype=np.int64)
        for step in range(3):
            accepted, _ = client.push_gradients(
                {"a": np.ones(4, np.float32),
                 "b": np.ones(4, np.float32)},
                {"emb": (np.ones((8, 4), np.float32), ids)},
                version=step,
            )
            assert accepted
        rows_before = client.pull_embedding_vectors("emb", ids)

        victim = manager._procs[0]
        os.kill(victim.pid, signal.SIGKILL)
        assert wait_until(
            lambda: manager._procs[0].pid != victim.pid
        ), "PS shard was not relaunched"

        # Fresh channel to the relaunched shard on the SAME port.
        client2 = make_client(manager)
        rows_after = client2.pull_embedding_vectors("emb", ids)
        # Shard 0 owns the even ids; its rows must come back from the
        # checkpoint, not re-initialize to zeros.
        np.testing.assert_allclose(rows_after, rows_before, rtol=1e-6)
        # And the relaunched shard keeps serving pushes.
        accepted, _ = client2.push_gradients(
            {"a": np.ones(4, np.float32), "b": np.ones(4, np.float32)},
            {"emb": (np.ones((8, 4), np.float32), ids)}, version=9,
        )
        assert accepted
    finally:
        manager.stop()


@pytest.mark.slow
def test_ps_relaunch_budget_exhausts(tmp_path):
    manager = PSManager(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=1.0",
        max_relaunch=1,
    )
    manager.start()
    try:
        make_client(manager)  # shard is up
        first = manager._procs[0]
        os.kill(first.pid, signal.SIGKILL)
        assert wait_until(lambda: manager._procs[0].pid != first.pid)
        second = manager._procs[0]
        os.kill(second.pid, signal.SIGKILL)
        # budget spent: the watcher reaps the corpse and declines to
        # relaunch — join it instead of sleeping a fixed interval
        import threading

        for t in threading.enumerate():
            if t.name.startswith("ps-watch"):
                t.join(timeout=15)
        assert manager._procs[0].pid == second.pid
        assert manager._procs[0].poll() is not None
    finally:
        manager.stop()
