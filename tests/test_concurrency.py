"""Thread-safety under concurrent pushes/pulls (reference pattern:
staleness_aware_test.py:25-90 with ThreadPoolExecutor).

The task-manager drill additionally runs under elastic-lint's runtime
lock-discipline tracer (tools/elastic_lint/runtime_tracer.py) — the
dynamic half of rule EL001: every access to the guarded queue state
observed during the drill must hold the lock."""

import os
import sys
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from elasticdl_tpu.worker.ps_client import PSClient
from tests.test_pserver import start_ps, stop_all

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # tools/ is repo tooling, not installed
    sys.path.insert(0, REPO)

from tools.elastic_lint.runtime_tracer import (  # noqa: E402
    LockDisciplineTracer,
)


def test_concurrent_async_pushes_all_apply():
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=True,
    )
    try:
        client.push_model(
            {"w%d" % i: np.zeros(4, np.float32) for i in range(8)},
            embedding_infos=[{"name": "emb", "dim": 4,
                              "initializer": "zeros"}],
        )
        n_threads, pushes_each = 8, 25

        def worker(tid):
            rng = np.random.RandomState(tid)
            for _ in range(pushes_each):
                dense = {"w%d" % i: np.full(4, 0.01, np.float32)
                         for i in range(8)}
                ids = rng.randint(0, 50, size=4).astype(np.int64)
                client.push_gradients(
                    dense,
                    {"emb": (np.full((4, 4), 0.01, np.float32), ids)},
                    version=0,
                )

        with ThreadPoolExecutor(n_threads) as pool:
            list(pool.map(worker, range(n_threads)))

        # every push applied exactly once: w = -lr * 0.01 * total_pushes
        total = n_threads * pushes_each
        _, version, dense = client.pull_dense_parameters(-1)
        for i in range(8):
            np.testing.assert_allclose(
                dense["w%d" % i], -0.01 * total, rtol=1e-4
            )
        # version counted once per push per involved shard set
        assert version == total
    finally:
        stop_all(servers)


def test_concurrent_pulls_during_pushes_no_torn_reads():
    client, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=0.5",
        use_async=True,
    )
    try:
        # all elements of w move together; a torn read would show
        # different values within one pulled array
        client.push_model({"w": np.zeros(1024, np.float32)})

        stop = False
        torn = []

        def pusher():
            for _ in range(50):
                client.push_gradients(
                    {"w": np.ones(1024, np.float32)}, version=0
                )

        def puller():
            while not stop:
                _, _, dense = client.pull_dense_parameters(-1)
                w = dense.get("w")
                if w is not None and len(set(w.tolist())) > 1:
                    torn.append(w.copy())

        with ThreadPoolExecutor(4) as pool:
            futures = [pool.submit(pusher) for _ in range(2)]
            probe = pool.submit(puller)
            for f in futures:
                f.result()
            stop = True
            probe.result()
        assert not torn, "torn parameter reads observed"
    finally:
        stop_all(servers)


def test_task_manager_concurrent_get_report():
    from elasticdl_tpu.master.task_manager import TaskManager

    tm = TaskManager(
        training_shards=[("f", 0, 4000)], records_per_task=10
    )

    def consume(worker_id):
        done = 0
        while True:
            task = tm.get(worker_id)
            if task is None:
                break
            tm.report(task.id, True)
            done += 1
        return done

    with LockDisciplineTracer() as tracer:
        tracer.register(tm, attrs=[
            "_todo", "_doing", "_task_id", "_epoch",
            "_train_end_callback_pending", "_train_end_callback_done",
            "_max_task_completed_time", "completed_counts",
            "failed_counts",
        ])
        with ThreadPoolExecutor(8) as pool:
            counts = list(pool.map(consume, range(8)))
        assert sum(counts) == 400
        assert tm.finished()
    # Dynamic EL001: no guarded attribute was touched off-lock during
    # the drill (would have been invisible to a pass/fail count).
    tracer.assert_clean()
    # Dynamic EL005: no lock-order cycle among the acquisition-order
    # edges the drill actually executed (one registered lock here, so
    # this also pins the "no edges at all" shape — a second lock
    # creeping into TaskManager's hot path would start recording).
    tracer.assert_ordered()
    assert tracer.lock_order_edges() == set()


def test_concurrent_pulls_race_pushes_on_same_table():
    """Embedding pulls run WITHOUT the servicer lock (round 2): hammer
    the same table with concurrent pulls and sparse pushes and assert
    rows are never torn — each ROW is either the old or the new value,
    never a mix (the native rw-lock's per-row atomicity, kernels.cc).
    Cross-row skew within one pull is allowed — async-SGD semantics,
    matching the reference Go table's RWMutex guarantees."""
    client, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=True,
    )
    try:
        client.push_model(
            {"w": np.zeros(2, np.float32)},
            embedding_infos=[{"name": "emb", "dim": 8,
                              "initializer": "zeros"}],
        )
        ids = np.arange(64, dtype=np.int64)
        stop = []

        def pusher():
            try:
                for _ in range(50):
                    client.push_gradients(
                        {}, {"emb": (np.full((64, 8), -1.0, np.float32),
                                     ids)},
                        version=0,
                    )
            finally:
                # always release the pullers, even on a pusher error —
                # otherwise the pool shutdown deadlocks the suite
                stop.append(True)

        torn = []

        def puller():
            while not stop:
                rows = client.pull_embedding_vectors("emb", ids)
                # each row must be a uniform SGD multiple: all 8 dims
                # equal (every push adds +1.0 to every dim of the row)
                spread = rows.max(axis=1) - rows.min(axis=1)
                if (spread > 0).any():
                    torn.append(rows)
                    return

        with ThreadPoolExecutor(5) as pool:
            futures = [pool.submit(puller) for _ in range(4)]
            pool.submit(pusher).result()
            for f in futures:
                f.result()
        assert not torn, "observed a torn embedding row"
    finally:
        stop_all(servers)


# -- EL011 runtime confirmation (sampled attribute-access records) ------


def test_race_fixture_confirmed_by_sampler_and_merged():
    """The dynamic half of EL011: drive the seeded fixture's two roots
    from two real threads under the tracer, then merge the sampled
    attribute-access records into the STATIC report — the flagged
    counter race must come back ``confirmed``, exactly like observed
    order edges confirm EL005 cycles."""
    from tests.fixture_race import (
        RacyTelemetryHub,
        drive_race_from_two_threads,
    )
    from tools.elastic_lint import build_program
    from tools.elastic_lint import el011_shared_state as el011

    hub = RacyTelemetryHub()
    try:
        with LockDisciplineTracer() as tracer:
            tracer.register(hub, attrs=["_total_reports", "_totals"])
            drive_race_from_two_threads(hub)
    finally:
        hub.close()
    assert ("RacyTelemetryHub", "_total_reports") \
        in tracer.race_confirmations()

    _, prog = build_program(
        [os.path.join(REPO, "tests", "fixture_race.py")])
    report = el011.build_report(prog)
    statically_flagged = {r["key"][-1] for r in report.races}
    assert statically_flagged == {"_total_reports", "_totals"}
    report.merge_observed(tracer.attr_access_records())
    confirmed = {r["key"][-1] for r in report.confirmed_races()}
    # the counter race is WITNESSED; the dict race stays static-only
    # (instance instrumentation sees the attribute fetch, not the
    # __setitem__ behind it — documented in the fixture)
    assert confirmed == {"_total_reports"}


def test_clean_fixture_sampler_confirms_nothing():
    """Counterpart drill: identical thread shape, RMWs under one lock,
    plus the atomic-publication rebind of ``_snapshot`` — the sampler
    must witness NO race (a bare setattr is a GIL-atomic rebind, not a
    lost update, so publication does not count as one)."""
    from tests.fixture_race_clean import (
        GuardedTelemetryHub,
        drive_clean_from_two_threads,
    )

    hub = GuardedTelemetryHub()
    try:
        with LockDisciplineTracer() as tracer:
            tracer.register(
                hub, attrs=["_total_reports", "_totals", "_snapshot"])
            drive_clean_from_two_threads(hub)
    finally:
        hub.close()
    assert tracer.race_confirmations() == set()
    # and the guarded counter really was exercised from two threads
    idents = {e[4] for e in tracer.events}
    assert len(idents) >= 2


def test_tracer_sampling_bounds_event_volume():
    """``sample_every=N`` keeps roughly 1/N of the access stream — the
    knob that makes tracing a hot attribute affordable in a drill."""
    from tests.fixture_race import RacyTelemetryHub

    dense = RacyTelemetryHub()
    sparse = RacyTelemetryHub()
    try:
        with LockDisciplineTracer() as tracer:
            tracer.register(dense, attrs=["_total_reports"])
            tracer.register(sparse, attrs=["_total_reports"],
                            sample_every=10)
            for _ in range(200):
                dense._flush_once()
                sparse._flush_once()
        dense_n = sum(1 for e in tracer.events
                      if e[0] == id(dense))
        sparse_n = sum(1 for e in tracer.events
                       if e[0] == id(sparse))
        assert dense_n >= 400          # read + write per increment
        assert 0 < sparse_n <= dense_n // 5
    finally:
        dense.close()
        sparse.close()
