"""In-jit PS embedding (models/ps_embedding_callback.py): pure_callback
pull + custom-VJP io_callback push against a REAL in-process PS."""

import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.models.ps_embedding_callback import PSEmbedding
from tests.test_pserver import start_ps, stop_all

DIM = 4


def _boot(lr=0.1):
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=%s" % lr,
        use_async=True,
    )
    infos = [{"name": "emb", "dim": DIM, "initializer": "zeros"}]
    client.push_model({"w": np.zeros(1, np.float32)},
                      embedding_infos=infos)
    return client, servers


def test_lookup_inside_jit_matches_direct_pull():
    client, servers = _boot()
    try:
        # seed some rows via a direct sparse push
        client.push_gradients(
            {}, {"emb": (-np.arange(8, dtype=np.float32)
                         .reshape(2, DIM),
                         np.array([3, 11], np.int64))}, version=0)
        emb = PSEmbedding(client, "emb", DIM)
        ids = jnp.array([3, 11, 999])

        @jax.jit
        def forward(ids, handle):
            return emb(ids, handle) * 2.0

        got = np.asarray(forward(ids, emb.handle))
        want = client.pull_embedding_vectors(
            "emb", np.array([3, 11, 999])) * 2.0
        np.testing.assert_allclose(got, want, rtol=1e-6)
        assert got.shape == (3, DIM)
    finally:
        stop_all(servers)


def test_backward_pushes_sparse_grads_to_ps():
    """grad(loss) through the jitted lookup pushes the sparse gradient
    to the PS: the rows move by -lr * dL/drow (async SGD), duplicate
    ids merge server-side — the reference's tape-rewiring semantics
    (embedding_delegate.py:232-281)."""
    lr = 0.1
    client, servers = _boot(lr=lr)
    try:
        emb = PSEmbedding(client, "emb", DIM)
        ids = jnp.array([7, 9, 7])  # duplicate id 7 must merge

        @jax.jit
        def loss_fn(handle):
            rows = emb(ids, handle)
            return rows.sum()

        g = jax.grad(loss_fn)(emb.handle)
        # dL/drow = scale = 1.0 for every row; id 7 appears twice ->
        # merged grad 2.0; async SGD applies immediately.
        rows = client.pull_embedding_vectors("emb", np.array([7, 9]))
        np.testing.assert_allclose(rows[0], -lr * 2.0 * np.ones(DIM),
                                   rtol=1e-6)
        np.testing.assert_allclose(rows[1], -lr * 1.0 * np.ones(DIM),
                                   rtol=1e-6)
        assert float(g) == 0.0  # rows were zeros at pull time
    finally:
        stop_all(servers)


def test_trains_a_model_end_to_end():
    """A tiny regression model whose embedding lives on the PS and
    whose dense weight lives in the jit step: both learn."""
    client, servers = _boot(lr=0.05)
    try:
        emb = PSEmbedding(client, "emb", DIM)
        ids = jnp.array([1, 2, 3, 4])
        targets = jnp.array([1.0, -1.0, 0.5, 2.0])

        @jax.jit
        def loss_fn(params, ids, targets):
            rows = emb(ids, params["emb_handle"])
            preds = rows @ params["w"]
            return jnp.mean((preds - targets) ** 2)

        params = {"w": jnp.ones((DIM,), jnp.float32),
                  "emb_handle": emb.handle}
        first = None
        for _ in range(60):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, ids, targets)
            if first is None:
                first = float(loss)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, params, grads)
        assert float(loss) < first * 0.05, (first, float(loss))
    finally:
        stop_all(servers)
