"""Elasticity manager under test (VERDICT r1 #3).

Unit-tests the worker state-flow table and WorkerManager's relaunch
decisions against a fake backend, then drills the real thing: a managed
job with process workers where one is SIGKILLed mid-run (reference
semantics: pod_state.py:28-106, master_test.py:51-107).
"""

import os
import signal
import threading
import time

import pytest

from elasticdl_tpu.master import worker_state as ws
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.master.worker_manager import (
    ProcessWorkerBackend,
    WorkerManager,
)


# -- state-flow table ---------------------------------------------------------

def test_flow_preempted_relaunches():
    flow = ws.get_flow(ws.RUNNING, ws.EV_PREEMPTED)
    assert flow.to_status == ws.DELETED and flow.should_relaunch


def test_flow_oom_never_relaunches():
    """Exit-137 analog: an OOM-killed worker would just OOM again
    (reference pod_manager.py:102-115)."""
    flow = ws.get_flow(ws.RUNNING, ws.EV_OOM)
    assert flow.to_status == ws.FAILED and not flow.should_relaunch


def test_flow_clean_exit_no_relaunch():
    flow = ws.get_flow(ws.RUNNING, ws.EV_EXIT_0)
    assert flow.to_status == ws.SUCCEEDED and not flow.should_relaunch


def test_flow_error_exit_relaunches_from_pending_and_running():
    for status in (ws.PENDING, ws.RUNNING):
        flow = ws.get_flow(status, ws.EV_EXIT_ERR)
        assert flow.to_status == ws.FAILED and flow.should_relaunch


def test_flow_unknown_transition_is_none():
    assert ws.get_flow(ws.SUCCEEDED, ws.EV_EXIT_ERR) is None


# -- WorkerManager against a fake backend ------------------------------------

class FakeRef:
    def __init__(self, worker_id):
        self.worker_id = worker_id
        self._exit = threading.Event()
        self.code = None

    def finish(self, code):
        self.code = code
        self._exit.set()


class FakeBackend:
    def __init__(self):
        self.refs = {}

    def launch(self, worker_id, master_addr, slot=None):
        ref = FakeRef(worker_id)
        self.refs[worker_id] = ref
        return ref

    def wait(self, ref):
        ref._exit.wait()
        return ref.code

    def kill(self, ref, force=False):
        ref.finish(-signal.SIGKILL if force else -signal.SIGTERM)

    def is_alive(self, ref):
        return not ref._exit.is_set()


from tests.util import wait_until


def make_manager(num_workers=2, **kwargs):
    backend = FakeBackend()
    mgr = WorkerManager(backend, num_workers=num_workers, **kwargs)
    mgr.set_master_addr("localhost:0")
    mgr.start()
    return backend, mgr


def test_crash_relaunches_with_fresh_id():
    backend, mgr = make_manager(2)
    backend.refs[0].finish(1)  # worker 0 crashes
    assert wait_until(lambda: 2 in backend.refs)
    assert mgr._workers[2].relaunch_count == 1
    assert sorted(backend.refs) == [0, 1, 2]  # ids never reused
    mgr.stop()


def test_relaunch_budget_exhausts():
    backend, mgr = make_manager(1, max_relaunch_count=2)
    for wid in (0, 1, 2):
        assert wait_until(lambda: wid in backend.refs)
        backend.refs[wid].finish(1)
        # allow the watcher to process the exit
        assert wait_until(
            lambda: not backend.is_alive(backend.refs[wid])
        )
    # budget spent after 2 relaunches: no worker 3, job is stalled
    assert wait_until(lambda: mgr.all_workers_done())
    assert 3 not in backend.refs
    mgr.stop()


def test_oom_killed_worker_not_relaunched():
    backend, mgr = make_manager(1)
    backend.refs[0].finish(137)  # container OOM exit code
    assert wait_until(lambda: mgr.all_workers_done())
    assert list(backend.refs) == [0]
    mgr.stop()


def test_cluster_env_fn_emits_tf_config_per_slot():
    """The foreign-runtime cluster-spec hook (reference
    pod_manager.py:405-422): every launch carries a TF_CONFIG built
    from the manager's cluster view, and a RELAUNCHED worker inherits
    its slot's task index — the identity the foreign runtime knows it
    by — not its fresh worker id."""
    import json

    from elasticdl_tpu.master.cluster_spec_env import make_tf_config_fn

    class EnvRecordingBackend(FakeBackend):
        def __init__(self):
            super().__init__()
            self.envs = {}

        def launch(self, worker_id, master_addr, slot=None,
                   extra_env=None):
            self.envs[worker_id] = dict(extra_env or {})
            return super().launch(worker_id, master_addr, slot=slot)

    hosts = ["w-0.ns.svc:50002", "w-1.ns.svc:50002"]
    backend = EnvRecordingBackend()
    mgr = WorkerManager(
        backend, num_workers=2,
        cluster_env_fn=make_tf_config_fn(hosts, ps_hosts=["ps0:2222"]),
    )
    mgr.set_master_addr("localhost:0")
    mgr.start()
    for wid in (0, 1):
        cfg = json.loads(backend.envs[wid]["TF_CONFIG"])
        assert cfg["cluster"] == {"worker": hosts, "ps": ["ps0:2222"]}
        assert cfg["task"] == {"type": "worker", "index": wid}

    backend.refs[0].finish(1)  # crash slot 0's worker
    assert wait_until(lambda: 2 in backend.envs)
    cfg = json.loads(backend.envs[2]["TF_CONFIG"])
    assert cfg["task"]["index"] == 0  # slot identity, not worker id 2
    mgr.stop()


def test_preempt_drill_is_not_done_window():
    """Between the SIGKILL and the relaunch, all_workers_done must stay
    False (relaunch_pending masks the dead-but-recovering window), or the
    master would abort a healthy job."""
    backend, mgr = make_manager(1)
    seen_done = []
    orig_kill = backend.kill

    def kill_and_probe(ref, force=False):
        orig_kill(ref, force=force)
        seen_done.append(mgr.all_workers_done())

    backend.kill = kill_and_probe
    mgr.preempt_worker(0)
    assert wait_until(lambda: 1 in backend.refs)
    assert seen_done == [False]
    mgr.stop()


def test_exit_callbacks_fire_with_relaunch_decision():
    backend, mgr = make_manager(1)
    events = []
    mgr.add_exit_callback(lambda wid, rl: events.append((wid, rl)))
    backend.refs[0].finish(1)
    assert wait_until(lambda: 1 in backend.refs)
    backend.refs[1].finish(0)
    assert wait_until(lambda: len(events) == 2)
    assert events == [(0, True), (1, False)]
    mgr.stop()


# -- end-to-end drills with real processes -----------------------------------

def _managed_job(records, num_workers, worker_args_extra=(), num_epochs=1):
    from elasticdl_tpu.data.factory import create_data_reader

    reader = create_data_reader(
        "synthetic_mnist:%d" % records, records_per_shard=128
    )
    task_manager = TaskManager(
        training_shards=reader.create_shards(), records_per_task=128,
        num_epochs=num_epochs,
    )
    worker_args = [
        "--model_zoo", "mnist",
        "--data_origin", "synthetic_mnist:%d" % records,
        "--batch_size", "32", "--num_minibatches_per_task", "4",
        "--num_epochs", str(num_epochs),
    ] + list(worker_args_extra)
    worker_manager = WorkerManager(
        ProcessWorkerBackend(worker_args=worker_args),
        num_workers=num_workers,
    )
    return Master(task_manager, worker_manager=worker_manager)


@pytest.mark.slow
def test_sigkill_mid_job_recovers_and_completes():
    """The headline drill, in-suite: SIGKILL a real worker process
    mid-job; the job must relaunch it under a fresh id and finish with
    zero permanently-failed tasks."""
    master = _managed_job(records=2048, num_workers=2, num_epochs=2)
    launched = []
    master.worker_manager.add_start_callback(launched.append)
    master.prepare()

    def preempt():
        # wait for a worker to be mid-training, then kill it
        deadline = time.time() + 60
        while time.time() < deadline:
            counts = master.task_manager.counts()
            if counts["completed"].get(0, 0) >= 1:
                break
            time.sleep(0.1)
        master.worker_manager.preempt_worker(0, force=True)

    killer = threading.Thread(target=preempt)
    killer.start()
    rc = master.run()
    killer.join()
    counts = master.task_manager.counts()
    assert rc == 0
    assert counts["todo"] == 0 and counts["doing"] == 0
    assert all(v == 0 for v in counts["failed"].values())
    assert 2 in launched  # replacement got a fresh id, not a reused one


@pytest.mark.slow
def test_all_workers_crashing_aborts_job():
    """Workers that can never start (bad zoo module) exhaust the
    relaunch budget; master.run() must return 1, not hang (the
    all_workers_done stall-abort, master.py:85-98)."""
    master = _managed_job(records=256, num_workers=1)
    master.worker_manager._backend._worker_args[1] = "no_such_model"
    master.prepare()
    rc = master.run()
    assert rc == 1
