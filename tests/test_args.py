"""Flag-system round-trips (reference args_test / arg_parser_test)."""

from elasticdl_tpu.utils.args import (
    build_arguments_from_parsed_result,
    parse_master_args,
    parse_opt_args,
    parse_ps_args,
    parse_worker_args,
)


def test_master_args_roundtrip_to_worker():
    args = parse_master_args([
        "--model_zoo", "deepfm", "--batch_size", "64",
        "--num_epochs", "3", "--shuffle", "true",
        "--distribution_strategy", "ps", "--num_workers", "2",
    ])
    from elasticdl_tpu.master.main import _MASTER_ONLY_ARGS

    flags = build_arguments_from_parsed_result(
        args, filter_args=_MASTER_ONLY_ARGS,
    )
    worker_args = parse_worker_args(flags)
    assert worker_args.model_zoo == "deepfm"
    assert worker_args.batch_size == 64
    assert worker_args.num_epochs == 3
    assert worker_args.distribution_strategy == "ps"


def test_bool_flags_survive_roundtrip():
    args = parse_master_args(["--use_bf16", "True"])
    flags = build_arguments_from_parsed_result(args)
    again = parse_master_args(flags)
    assert again.use_bf16 is True
    args = parse_master_args(["--use_bf16", "false"])
    flags = build_arguments_from_parsed_result(args)
    assert parse_master_args(flags).use_bf16 is False


def test_ps_args_and_opt_args():
    args = parse_ps_args([
        "--opt_type", "adam",
        "--opt_args", "learning_rate=0.01;beta_1=0.95;amsgrad=true",
        "--grads_to_wait", "4", "--use_async", "false",
    ])
    assert args.use_async is False and args.grads_to_wait == 4
    parsed = parse_opt_args(args.opt_args)
    assert parsed["learning_rate"] == 0.01
    assert parsed["beta_1"] == 0.95
    assert parsed["amsgrad"] == "true"
