"""Iris from a SQL table end-to-end — the odps_iris zoo parity path."""

import numpy as np

from elasticdl_tpu.client.k8s_renderer import parse_resource_string
from elasticdl_tpu.data.sql_reader import SQLTableDataReader, SQLTableWriter
from elasticdl_tpu.models import iris
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer
from tests.test_utils import create_master, create_master_client
from elasticdl_tpu.worker.worker import Worker


def test_iris_trains_from_sql_table(tmp_path):
    db = str(tmp_path / "iris.db")
    rng = np.random.RandomState(0)
    writer = SQLTableWriter(db, "iris",
                            ["f0", "f1", "f2", "f3", "label"])
    centers = np.array([[5.0, 3.4, 1.5, 0.2], [6.6, 3.0, 5.6, 2.1]])
    rows = []
    for _ in range(128):
        y = rng.randint(2)
        x = centers[y] + rng.randn(4) * 0.2
        rows.append(list(x) + [y])
    writer.write(rows)
    writer.close()

    reader = SQLTableDataReader(db, "iris", records_per_shard=32)
    master = create_master(
        training_shards=reader.create_shards(), records_per_task=32,
        num_epochs=4,
    )
    try:
        mc = create_master_client(master)
        spec = iris.model_spec(learning_rate=0.05, num_classes=2)
        trainer = CollectiveTrainer(spec, batch_size=32)
        worker = Worker(mc, reader, spec, trainer, batch_size=32)
        worker.run()
        assert master.task_manager.finished()
        xs, ys = spec.feed(rows)
        out, labels = trainer.evaluate_minibatch(xs[:32], ys[:32])
        assert (np.argmax(out, -1) == labels).mean() > 0.8
    finally:
        master.stop()


def test_parse_resource_string():
    out = parse_resource_string("cpu=1,memory=4096Mi,google.com/tpu=8")
    assert out == {"cpu": "1", "memory": "4096Mi",
                   "google.com/tpu": "8"}
