"""PS-backed shared embedding service (serving/embedding_service.py):
the byte-budgeted version-keyed hot-row LRU, read-only lookups that
never grow the training table, bit-identity with the exported-table
lookup path, generation-stamped invalidation after a PS restart, and
the /statz /metrics cache counters."""

import http.client
import json
import os
import threading

import numpy as np

from elasticdl_tpu.proto import rpc
from elasticdl_tpu.ps.optimizer import create_optimizer
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.serving.embedding_service import (
    HotRowCache,
    PSEmbeddingService,
)
from elasticdl_tpu.serving.export import export_servable
from elasticdl_tpu.serving.server import ModelEndpoint, build_server
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.ps_client import PSClient

DIM = 4


def start_ps(num_ps=1, generation=1):
    servers, servicers, channels = [], [], []
    for i in range(num_ps):
        servicer = PserverServicer(
            Parameters(), create_optimizer("sgd", "learning_rate=0.1"),
            ps_id=i, num_ps=num_ps, generation=generation,
        )
        server = grpc_utils.build_server(max_workers=8)
        rpc.add_pserver_servicer(servicer, server)
        port = server.add_insecure_port("[::]:0")
        server.start()
        channel = grpc_utils.build_channel("localhost:%d" % port)
        grpc_utils.wait_for_channel_ready(channel)
        servers.append(server)
        servicers.append(servicer)
        channels.append(channel)
    return PSClient(channels), servicers, servers


def stop_all(servers):
    for s in servers:
        s.stop(grace=None)


def seed_table(client, n_rows, name="users"):
    """Create + initialize rows 0..n-1 the way training does (lazy
    init through a training-mode pull), returning the row matrix."""
    client.push_model({}, embedding_infos=[
        {"name": name, "dim": DIM, "initializer": "uniform"}])
    return client.pull_embedding_vectors(name, np.arange(n_rows))


# -- HotRowCache unit --------------------------------------------------


def test_cache_lru_eviction_is_byte_budgeted():
    timing = Timing()
    row_bytes = DIM * 4
    cache = HotRowCache(3 * row_bytes, timing=timing)
    key = (1, 0)
    rows = np.arange(5 * DIM, dtype=np.float32).reshape(5, DIM)
    cache.put_many(key, "t", [0, 1, 2], rows[:3])
    assert cache.stats()["bytes"] == 3 * row_bytes
    # Touch id 0 so id 1 is the LRU victim.
    got, missing = cache.get_many(key, "t", [0])
    assert missing == [] and np.array_equal(got[0], rows[0])
    cache.put_many(key, "t", [3], rows[3:4])
    stats = cache.stats()
    assert stats["bytes"] == 3 * row_bytes
    assert stats["evicted_rows"] == 1
    _, missing = cache.get_many(key, "t", [1])
    assert missing == [0]          # id 1 was evicted
    _, missing = cache.get_many(key, "t", [0, 2, 3])
    assert missing == []           # the survivors


def test_cache_version_key_invalidation_and_stale_put():
    cache = HotRowCache(1 << 20)
    rows = np.ones((2, DIM), np.float32)
    cache.put_many((1, 0), "t", [0, 1], rows)
    # Version flip (model hot-swap): wholesale drop, counted.
    got, missing = cache.get_many((2, 0), "t", [0, 1])
    assert missing == [0, 1]
    assert cache.stats()["invalidations"] == 1
    # Generation-epoch flip (PS restart) re-keys the same way.
    cache.put_many((2, 0), "t", [0], rows[:1])
    _, missing = cache.get_many((2, 1), "t", [0])
    assert missing == [0]
    assert cache.stats()["invalidations"] == 2
    # A put under a DEAD key (another thread re-keyed mid-pull)
    # inserts nothing.
    cache.put_many((1, 0), "t", [5], rows[:1])
    assert cache.stats()["rows"] == 0


def test_cache_disabled_at_zero_budget():
    cache = HotRowCache(0)
    cache.put_many((1, 0), "t", [0], np.ones((1, DIM), np.float32))
    _, missing = cache.get_many((1, 0), "t", [0])
    assert missing == [0]


# -- PS-backed service -------------------------------------------------


def test_ps_lookup_bit_identical_to_export_path(tmp_path):
    """The acceptance gate: a table served straight from the PS (never
    exported to disk) returns rows BIT-IDENTICAL to the exported-table
    lookup path, unknown ids included."""
    client, servicers, servers = start_ps()
    try:
        trained = seed_table(client, 8)
        # Export the SAME table into a servable (the old path)...
        export_dir = os.path.join(str(tmp_path), "e")
        export_servable(
            export_dir, lambda p, x: x @ p["w"],
            {"w": np.zeros((2, 2), np.float32)},
            np.zeros((1, 2), np.float32), model_name="m",
            embeddings={"users": (np.arange(8), trained)},
            platforms=("cpu",),
        )
        endpoint = ModelEndpoint(export_dir)
        # ...and serve it from the PS through the service (the new
        # path), cache on.
        service = PSEmbeddingService(client, cache_bytes=1 << 20)
        try:
            probe = np.array([3, 0, 7, 123456, 5, 3])
            via_export = endpoint.lookup(
                {"table": "users", "ids": probe.tolist()})
            via_ps = service.lookup("users", probe)
            np.testing.assert_array_equal(
                np.asarray(via_export["vectors"], np.float32), via_ps)
            # Second pass serves the hot ids from cache — still
            # bit-identical.
            np.testing.assert_array_equal(
                service.lookup("users", probe), via_ps)
            assert service.stats()["hits"] > 0
        finally:
            endpoint.close()
    finally:
        stop_all(servers)


def test_read_only_lookup_never_grows_the_table():
    client, servicers, servers = start_ps()
    try:
        seed_table(client, 4)
        table = servicers[0]._params.embeddings["users"]
        assert len(table) == 4
        service = PSEmbeddingService(client, cache_bytes=1 << 20)
        out = service.lookup("users", np.array([999999, 2]))
        assert (out[0] == 0).all()
        assert len(table) == 4          # no lazy init from serving
        assert servicers[0].counters["pull_embedding_ro"] >= 1
        # The training-mode pull still lazily initializes.
        client.pull_embedding_vectors("users", np.array([999999]))
        assert len(table) == 5
    finally:
        stop_all(servers)


def test_ps_restart_generation_invalidates_cache():
    """The lookup path rides PS generations (docs/ps_recovery.md): the
    read-only pull responses are generation-stamped, so an
    embedding-only client notices a crash-restore rollback and drops
    rows read from the dead incarnation."""
    client, servicers, servers = start_ps(generation=1)
    try:
        seed_table(client, 4)
        # probe_interval 0: every all-hit lookup still pays one probe
        # pull, so the restart is noticed immediately in the test (the
        # default cadence bounds the staleness window at ~2 s).
        service = PSEmbeddingService(client, cache_bytes=1 << 20,
                                     probe_interval_secs=0.0)
        service.set_version(1)
        service.lookup("users", np.arange(4))
        assert service.lookup("users", np.arange(4)) is not None
        stats = service.stats()
        assert stats["hits"] >= 4 and stats["rows"] == 4
        assert client.known_generation(0) == 1
        # "Restart" the shard: new incarnation, rolled-back rows.
        servicers[0].generation = 2
        servicers[0]._params.embeddings["users"].set(
            np.arange(4), np.zeros((4, DIM), np.float32))
        # The freshness probe's pull carries the new generation stamp;
        # the service re-keys MID-LOOKUP and re-pulls the whole batch,
        # so not even this first post-restart lookup mixes incarnations.
        out = service.lookup("users", np.arange(4))
        np.testing.assert_array_equal(out,
                                      np.zeros((4, DIM), np.float32))
        assert service.stats()["invalidations"] >= 1
        assert client.generation_epoch == 1
        counters = service.timing.counters()
        assert counters.get("emb_cache.freshness_probes", 0) >= 1
        assert counters.get("emb_cache.epoch_repulls", 0) == 1
    finally:
        stop_all(servers)


def test_set_version_invalidates_on_hot_swap():
    client, servicers, servers = start_ps()
    try:
        seed_table(client, 4)
        service = PSEmbeddingService(client, cache_bytes=1 << 20)
        service.set_version(1)
        service.lookup("users", np.arange(4))
        assert service.stats()["rows"] == 4
        service.set_version(2)      # fleet commit calls this
        service.lookup("users", np.arange(4))
        assert service.stats()["invalidations"] == 1
    finally:
        stop_all(servers)


def test_endpoint_routes_unexported_table_to_ps_and_statz(tmp_path):
    """:lookup for a table the export does not embed resolves through
    the PS service; the export's own tables keep the old path; the
    cache counters surface on /statz and /metrics."""
    client, servicers, servers = start_ps()
    try:
        trained = seed_table(client, 8, name="ps_only")
        export_dir = os.path.join(str(tmp_path), "e")
        export_servable(
            export_dir, lambda p, x: x @ p["w"],
            {"w": np.zeros((2, 2), np.float32)},
            np.zeros((1, 2), np.float32), model_name="m", version=5,
            embeddings={"local": (np.array([1, 2]),
                                  np.ones((2, 3), np.float32))},
            platforms=("cpu",),
        )
        service = PSEmbeddingService(client, cache_bytes=1 << 20)
        endpoint = ModelEndpoint(export_dir,
                                 embedding_service=service)
        server = build_server(endpoint, port=0)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            conn.request("POST", "/v1/models/m:lookup",
                         body=json.dumps({"table": "ps_only",
                                          "ids": [0, 3, 7]}))
            res = json.loads(conn.getresponse().read())
            assert res["source"] == "ps"
            assert res["model_version"] == 5
            np.testing.assert_array_equal(
                np.asarray(res["vectors"], np.float32),
                trained[[0, 3, 7]])
            conn.request("POST", "/v1/models/m:lookup",
                         body=json.dumps({"table": "local",
                                          "ids": [1]}))
            res = json.loads(conn.getresponse().read())
            assert res["source"] == "export"
            assert res["vectors"] == [[1.0, 1.0, 1.0]]
            # The endpoint keyed the service at ITS serving version.
            assert service.stats()["version_key"][0] == 5
            conn.request("GET", "/statz")
            statz = json.loads(conn.getresponse().read())
            cache = statz["models"]["m"]["emb_cache"]
            assert cache["misses"] >= 3
            conn.request("GET", "/metrics")
            metrics = conn.getresponse().read().decode()
            assert "elasticdl_serving_emb_cache_bytes" in metrics
            assert "elasticdl_serving_emb_cache_hit_ratio" in metrics
            conn.close()
        finally:
            server.shutdown()
            server.server_close()
            endpoint.close()
    finally:
        stop_all(servers)


def test_empty_ids_and_learned_dim():
    client, servicers, servers = start_ps()
    try:
        seed_table(client, 2)
        service = PSEmbeddingService(client, cache_bytes=1 << 20)
        out = service.lookup("users", np.array([], np.int64))
        assert out.shape == (0, DIM)
    finally:
        stop_all(servers)
