"""elastic-lint rule suite: every rule catches its known-bad fixture
and stays quiet on the matching known-good one; the runtime tracer
flags a deliberately unsynchronized counter; and the repo itself is
lint-clean (the tier-1 CI gate for the whole checker)."""

import os
import sys
import textwrap
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # tools/ is not an installed package
    sys.path.insert(0, REPO)

from tools.elastic_lint import (  # noqa: E402
    DEFAULT_BASELINE,
    check_source,
    run_paths,
)
from tools.elastic_lint.runtime_tracer import (  # noqa: E402
    LockDisciplineTracer,
)


def rules_hit(source):
    return {f.rule for f in check_source(textwrap.dedent(source))}


# -- EL001 lock-discipline ----------------------------------------------


EL001_BAD = """
    import threading

    class Queueish:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._closed = False

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def drain(self):
            out = list(self._items)   # read outside the lock
            return out

        def close(self):
            self._closed = True       # written outside the lock

        def is_closed(self):
            with self._lock:
                return self._closed
"""

EL001_GOOD = """
    import threading

    class Queueish:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._closed = False

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def drain(self):
            with self._lock:
                return list(self._items)

        def _drain_locked(self):
            return list(self._items)  # caller-holds-lock convention

        def close(self):
            with self._lock:
                self._closed = True
"""


def test_el001_flags_unlocked_access():
    findings = check_source(textwrap.dedent(EL001_BAD))
    symbols = {f.symbol for f in findings if f.rule == "EL001"}
    assert "Queueish.drain._items" in symbols
    assert "Queueish.close._closed" in symbols


def test_el001_quiet_on_disciplined_class():
    assert "EL001" not in rules_hit(EL001_GOOD)


def test_el001_inline_suppression_requires_reason():
    suppressed = EL001_BAD.replace(
        "out = list(self._items)   # read outside the lock",
        "out = list(self._items)  # elint: disable=EL001 -- snapshot",
    )
    findings = check_source(textwrap.dedent(suppressed))
    assert not any(f.symbol == "Queueish.drain._items"
                   for f in findings)
    reasonless = EL001_BAD.replace(
        "out = list(self._items)   # read outside the lock",
        "out = list(self._items)  # elint: disable=EL001",
    )
    findings = check_source(textwrap.dedent(reasonless))
    # no silent pass: the naked pragma is itself reported
    assert any(f.rule == "ELSUP" for f in findings)


# -- EL002 servicer-safety ----------------------------------------------


EL002_BAD = """
    class ThingServicer:
        def get_thing(self, request, _context=None):
            return request.id
"""

EL002_GOOD = """
    from elasticdl_tpu.utils.grpc_utils import rpc_error_guard

    class ThingServicer:
        @rpc_error_guard
        def get_thing(self, request, _context=None):
            return request.id

        def helper(self, a, b):
            return a + b
"""


def test_el002_flags_unguarded_rpc():
    assert "EL002" in rules_hit(EL002_BAD)


def test_el002_quiet_on_guarded_rpc():
    assert "EL002" not in rules_hit(EL002_GOOD)


def test_el002_guard_wrapper_aborts_with_status():
    class FakeContext:
        def __init__(self):
            self.code = None

        def abort(self, code, details):
            self.code = code
            raise RuntimeError("aborted: %s" % details)

    from elasticdl_tpu.utils.grpc_utils import rpc_error_guard

    class Servicer:
        @rpc_error_guard
        def boom(self, request, _context=None):
            raise ValueError("kaput")

    ctx = FakeContext()
    try:
        Servicer().boom(object(), ctx)
    except RuntimeError as e:
        assert "kaput" in str(e)
    else:
        raise AssertionError("abort did not propagate")
    assert ctx.code is not None


# -- EL003 jit-purity ---------------------------------------------------


EL003_BAD = """
    import jax

    def build(self, log):
        def step(params, batch):
            print("tracing", params)      # trace-time only
            log["count"] += 1             # closed-over host mutation
            return params

        return jax.jit(step)
"""

EL003_GOOD = """
    import jax

    def build(self):
        def step(params, batch):
            acc = {}
            acc["loss"] = batch.sum()     # local, fine
            return params, acc

        return jax.jit(step, donate_argnums=(0,))
"""


def test_el003_flags_impure_traced_fn():
    findings = [f for f in check_source(textwrap.dedent(EL003_BAD))
                if f.rule == "EL003"]
    messages = " ".join(f.message for f in findings)
    assert "print" in messages
    assert "closed-over host state 'log'" in messages


def test_el003_quiet_on_pure_traced_fn():
    assert "EL003" not in rules_hit(EL003_GOOD)


# -- EL004 thread-hygiene ----------------------------------------------


EL004_BAD = """
    import threading

    def run(target):
        worker = threading.Thread(target=target)
        worker.start()
"""

EL004_GOOD = """
    import threading

    def run(target):
        worker = threading.Thread(target=target, daemon=True)
        worker.start()

    def run_and_wait(target):
        worker = threading.Thread(target=target)
        worker.start()
        worker.join()
"""


def test_el004_flags_unjoined_nondaemon_thread():
    assert "EL004" in rules_hit(EL004_BAD)


def test_el004_quiet_on_daemonized_or_joined():
    assert "EL004" not in rules_hit(EL004_GOOD)


# -- runtime tracer -----------------------------------------------------


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump_racy(self):
        self.value += 1  # deliberately unsynchronized

    def bump_locked(self):
        with self._lock:
            self.value += 1


def _hammer(fn, n_threads=8, n_calls=200):
    # Dedicated threads (not a pool): a pool worker can steal every
    # task and leave the access log single-threaded, which is exactly
    # the pattern the tracer rightly considers race-free.
    start = threading.Barrier(n_threads)

    def body():
        start.wait()
        for _ in range(n_calls):
            fn()

    threads = [threading.Thread(target=body) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_tracer_flags_unsynchronized_counter():
    counter = _Counter()
    with LockDisciplineTracer() as tracer:
        tracer.register(counter, attrs=["value"])
        _hammer(counter.bump_racy)
    problems = tracer.violations()
    assert problems, "racy counter not flagged"
    assert any(attr == "value" for _, attr, _ in problems)


def test_tracer_quiet_on_locked_counter():
    counter = _Counter()
    with LockDisciplineTracer() as tracer:
        tracer.register(counter, attrs=["value"])
        _hammer(counter.bump_locked)
    tracer.assert_clean()
    assert counter.value == 8 * 200


def test_tracer_restores_class_on_exit():
    counter = _Counter()
    with LockDisciplineTracer() as tracer:
        tracer.register(counter, attrs=["value"])
        assert type(counter).__name__ == "Traced_Counter"
    assert type(counter) is _Counter
    counter.bump_locked()  # still functional un-instrumented
    assert counter.value == 1


# -- the repo gate ------------------------------------------------------


def test_repo_is_lint_clean():
    """Tier-1 enforcement: the package must stay clean under
    EL001-EL004 (modulo the justified baseline).  A regression here
    means a new unsynchronized access, unguarded servicer RPC, impure
    traced function, or shutdown-less thread entered the codebase."""
    findings = run_paths(
        [os.path.join(REPO, "elasticdl_tpu"),
         os.path.join(REPO, "tools"),
         # The PS overlap bench spawns servers and drives the pipelined
         # trainer's thread machinery — hold it to the same bar.
         os.path.join(REPO, "bench_ps_wire.py")],
        baseline_path=DEFAULT_BASELINE,
    )
    assert not findings, "\n".join(
        "%s:%d: %s %s" % (f.path, f.line, f.rule, f.message)
        for f in findings)
