"""elastic-lint rule suite: every rule catches its known-bad fixture
and stays quiet on the matching known-good one; the runtime tracer
flags a deliberately unsynchronized counter; and the repo itself is
lint-clean (the tier-1 CI gate for the whole checker)."""

import os
import sys
import textwrap
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # tools/ is not an installed package
    sys.path.insert(0, REPO)

from tools.elastic_lint import (  # noqa: E402
    DEFAULT_BASELINE,
    check_source,
    run_paths,
)
from tools.elastic_lint.runtime_tracer import (  # noqa: E402
    LockDisciplineTracer,
)


def rules_hit(source):
    return {f.rule for f in check_source(textwrap.dedent(source))}


# -- EL001 lock-discipline ----------------------------------------------


EL001_BAD = """
    import threading

    class Queueish:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._closed = False

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def drain(self):
            out = list(self._items)   # read outside the lock
            return out

        def close(self):
            self._closed = True       # written outside the lock

        def is_closed(self):
            with self._lock:
                return self._closed
"""

EL001_GOOD = """
    import threading

    class Queueish:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._closed = False

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def drain(self):
            with self._lock:
                return list(self._items)

        def _drain_locked(self):
            return list(self._items)  # caller-holds-lock convention

        def close(self):
            with self._lock:
                self._closed = True
"""


def test_el001_flags_unlocked_access():
    findings = check_source(textwrap.dedent(EL001_BAD))
    symbols = {f.symbol for f in findings if f.rule == "EL001"}
    assert "Queueish.drain._items" in symbols
    assert "Queueish.close._closed" in symbols


def test_el001_quiet_on_disciplined_class():
    assert "EL001" not in rules_hit(EL001_GOOD)


def test_el001_inline_suppression_requires_reason():
    suppressed = EL001_BAD.replace(
        "out = list(self._items)   # read outside the lock",
        "out = list(self._items)  # elint: disable=EL001 -- snapshot",
    )
    findings = check_source(textwrap.dedent(suppressed))
    assert not any(f.symbol == "Queueish.drain._items"
                   for f in findings)
    reasonless = EL001_BAD.replace(
        "out = list(self._items)   # read outside the lock",
        "out = list(self._items)  # elint: disable=EL001",
    )
    findings = check_source(textwrap.dedent(reasonless))
    # no silent pass: the naked pragma is itself reported
    assert any(f.rule == "ELSUP" for f in findings)


# -- EL002 servicer-safety ----------------------------------------------


EL002_BAD = """
    class ThingServicer:
        def get_thing(self, request, _context=None):
            return request.id
"""

EL002_GOOD = """
    from elasticdl_tpu.utils.grpc_utils import rpc_error_guard

    class ThingServicer:
        @rpc_error_guard
        def get_thing(self, request, _context=None):
            return request.id

        def helper(self, a, b):
            return a + b
"""


def test_el002_flags_unguarded_rpc():
    assert "EL002" in rules_hit(EL002_BAD)


def test_el002_quiet_on_guarded_rpc():
    assert "EL002" not in rules_hit(EL002_GOOD)


def test_el002_guard_wrapper_aborts_with_status():
    class FakeContext:
        def __init__(self):
            self.code = None

        def abort(self, code, details):
            self.code = code
            raise RuntimeError("aborted: %s" % details)

    from elasticdl_tpu.utils.grpc_utils import rpc_error_guard

    class Servicer:
        @rpc_error_guard
        def boom(self, request, _context=None):
            raise ValueError("kaput")

    ctx = FakeContext()
    try:
        Servicer().boom(object(), ctx)
    except RuntimeError as e:
        assert "kaput" in str(e)
    else:
        raise AssertionError("abort did not propagate")
    assert ctx.code is not None


# -- EL003 jit-purity ---------------------------------------------------


EL003_BAD = """
    import jax

    def build(self, log):
        def step(params, batch):
            print("tracing", params)      # trace-time only
            log["count"] += 1             # closed-over host mutation
            return params

        return jax.jit(step)
"""

EL003_GOOD = """
    import jax

    def build(self):
        def step(params, batch):
            acc = {}
            acc["loss"] = batch.sum()     # local, fine
            return params, acc

        return jax.jit(step, donate_argnums=(0,))
"""


def test_el003_flags_impure_traced_fn():
    findings = [f for f in check_source(textwrap.dedent(EL003_BAD))
                if f.rule == "EL003"]
    messages = " ".join(f.message for f in findings)
    assert "print" in messages
    assert "closed-over host state 'log'" in messages


def test_el003_quiet_on_pure_traced_fn():
    assert "EL003" not in rules_hit(EL003_GOOD)


# -- EL004 thread-hygiene ----------------------------------------------


EL004_BAD = """
    import threading

    def run(target):
        worker = threading.Thread(target=target)
        worker.start()
"""

EL004_GOOD = """
    import threading

    def run(target):
        worker = threading.Thread(target=target, daemon=True)
        worker.start()

    def run_and_wait(target):
        worker = threading.Thread(target=target)
        worker.start()
        worker.join()
"""


def test_el004_flags_unjoined_nondaemon_thread():
    assert "EL004" in rules_hit(EL004_BAD)


def test_el004_quiet_on_daemonized_or_joined():
    assert "EL004" not in rules_hit(EL004_GOOD)


# -- runtime tracer -----------------------------------------------------


class _Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def bump_racy(self):
        self.value += 1  # deliberately unsynchronized

    def bump_locked(self):
        with self._lock:
            self.value += 1


def _hammer(fn, n_threads=8, n_calls=200):
    # Dedicated threads (not a pool): a pool worker can steal every
    # task and leave the access log single-threaded, which is exactly
    # the pattern the tracer rightly considers race-free.
    start = threading.Barrier(n_threads)

    def body():
        start.wait()
        for _ in range(n_calls):
            fn()

    threads = [threading.Thread(target=body) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def test_tracer_flags_unsynchronized_counter():
    counter = _Counter()
    with LockDisciplineTracer() as tracer:
        tracer.register(counter, attrs=["value"])
        _hammer(counter.bump_racy)
    problems = tracer.violations()
    assert problems, "racy counter not flagged"
    assert any(attr == "value" for _, attr, _ in problems)


def test_tracer_quiet_on_locked_counter():
    counter = _Counter()
    with LockDisciplineTracer() as tracer:
        tracer.register(counter, attrs=["value"])
        _hammer(counter.bump_locked)
    tracer.assert_clean()
    assert counter.value == 8 * 200


def test_tracer_restores_class_on_exit():
    counter = _Counter()
    with LockDisciplineTracer() as tracer:
        tracer.register(counter, attrs=["value"])
        assert type(counter).__name__ == "Traced_Counter"
    assert type(counter) is _Counter
    counter.bump_locked()  # still functional un-instrumented
    assert counter.value == 1


# -- EL005 lock-order ---------------------------------------------------


ABBA_FIXTURE = os.path.join(REPO, "tests", "fixture_abba.py")
CLEAN_FIXTURE = os.path.join(REPO, "tests",
                             "fixture_lock_order_clean.py")


def _fixture_source(path):
    with open(path, encoding="utf-8") as f:
        return f.read()


def test_el005_flags_seeded_abba_cycle():
    findings = check_source(_fixture_source(ABBA_FIXTURE),
                            "tests/fixture_abba.py")
    cycles = [f for f in findings if f.rule == "EL005"]
    assert cycles, "seeded ABBA deadlock not detected"
    assert cycles[0].symbol.startswith("cycle:")
    assert "LedgerAlpha._lock" in cycles[0].symbol
    assert "LedgerBeta._lock" in cycles[0].symbol


def test_el005_quiet_on_global_lock_order():
    findings = check_source(_fixture_source(CLEAN_FIXTURE),
                            "tests/fixture_lock_order_clean.py")
    assert "EL005" not in {f.rule for f in findings}


EL005_SELF_DEADLOCK = """
    import threading

    class Registry:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}

        def add(self, k, v):
            with self._lock:
                self._items[k] = v
                self.size()      # re-enters the non-reentrant Lock

        def size(self):
            with self._lock:
                return len(self._items)
"""


def test_el005_flags_lock_reentry_self_deadlock():
    findings = [f for f in check_source(
        textwrap.dedent(EL005_SELF_DEADLOCK)) if f.rule == "EL005"]
    assert findings and findings[0].symbol.startswith("self:")


def test_el005_rlock_reentry_is_legal():
    source = textwrap.dedent(EL005_SELF_DEADLOCK).replace(
        "threading.Lock()", "threading.RLock()")
    assert "EL005" not in rules_hit(source)


# -- EL006 blocking-under-lock ------------------------------------------


EL006_BAD = """
    import threading
    import time

    class Poller:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = 0

        def poll(self):
            with self._lock:
                self._state += 1
                self._settle()

        def _settle(self):
            time.sleep(0.1)
"""

EL006_GOOD = """
    import threading
    import time

    class Poller:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = 0

        def poll(self):
            with self._lock:
                self._state += 1
            self._settle()

        def _settle(self):
            time.sleep(0.1)
"""


def test_el006_flags_transitive_blocking_under_lock():
    findings = [f for f in check_source(textwrap.dedent(EL006_BAD))
                if f.rule == "EL006"]
    # flagged BOTH at the locked call site (the fix site) and nowhere
    # else — _settle itself holds no lock.
    assert findings
    assert all("_settle" in f.symbol or "sleep" in f.symbol
               for f in findings)
    assert any("time.sleep" in f.message for f in findings)


def test_el006_quiet_when_blocking_moves_outside():
    assert "EL006" not in rules_hit(EL006_GOOD)


def test_el006_direct_rpc_under_lock():
    source = """
        import threading
        from elasticdl_tpu.proto.rpc import MasterStub

        class Reporter:
            def __init__(self, channel):
                self._lock = threading.Lock()
                self._stub = MasterStub(channel)
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1
                    self._stub.report_version(None)
    """
    findings = [f for f in check_source(textwrap.dedent(source))
                if f.rule == "EL006"]
    assert findings and "RPC" in findings[0].message


# -- EL007 executor lifecycle -------------------------------------------


EL007_BAD = """
    from concurrent.futures import ThreadPoolExecutor

    class Pusher:
        def __init__(self):
            self._pool = ThreadPoolExecutor(max_workers=1)
"""

EL007_GOOD = """
    from concurrent.futures import ThreadPoolExecutor

    class Pusher:
        def __init__(self):
            self._pool = ThreadPoolExecutor(max_workers=1)

        def close(self):
            self._pool.shutdown(wait=True)

    def one_shot(fn):
        with ThreadPoolExecutor(max_workers=1) as pool:
            return pool.submit(fn).result()

    def build_server(grpc):
        # ownership handoff: grpc.server owns the pool's lifecycle
        return grpc.server(ThreadPoolExecutor(max_workers=4))
"""


def test_el007_flags_shutdownless_executor():
    findings = [f for f in check_source(textwrap.dedent(EL007_BAD))
                if f.rule == "EL007"]
    assert findings
    assert findings[0].symbol == "ThreadPoolExecutor:self._pool"


def test_el007_quiet_on_shutdown_with_and_handoff():
    assert "EL007" not in rules_hit(EL007_GOOD)


# -- EL008 RPC conformance ----------------------------------------------


EL008_CLIENT = """
    from elasticdl_tpu.proto import elastic_pb2 as pb
    from elasticdl_tpu.proto.rpc import MasterStub

    class Client:
        def __init__(self, channel):
            self._stub = MasterStub(channel)

        def good(self):
            req = pb.GetTaskRequest(worker_id=3)
            return self._stub.get_task(req)

        def unknown_method(self):
            return self._stub.fetch_task(None)

        def wrong_request(self):
            req = pb.ReportVersionRequest(model_version=1)
            return self._stub.get_task(req)

        def unknown_ctor_field(self):
            return pb.GetTaskRequest(worker_rank=3)

        def unknown_attr_field(self):
            req = pb.GetTaskRequest(worker_id=3)
            req.task_kind = 1
            return req

        def bogus_enum(self):
            return pb.TRAINING_V2
"""


def test_el008_flags_stub_and_field_drift():
    findings = [f for f in check_source(textwrap.dedent(EL008_CLIENT))
                if f.rule == "EL008"]
    messages = " ".join(f.message for f in findings)
    assert "fetch_task() is not a method" in messages
    assert "registers request type GetTaskRequest" in messages
    assert "unknown field 'worker_rank'" in messages
    assert "unknown field GetTaskRequest.task_kind" in messages
    assert "pb.TRAINING_V2 is neither" in messages
    # the valid call path produced no finding
    assert not any(".good" in f.symbol for f in findings)


def test_el008_proto_parser_reads_real_schema():
    from tools.elastic_lint.el008_rpc_conformance import (
        load_proto_fields,
    )

    fields, enums = load_proto_fields(REPO)
    assert "worker_id" in fields["GetTaskRequest"]
    assert "wire_dtype" in fields["TensorPB"]
    assert "exec_counters" in fields["ReportTaskResultRequest"]  # map
    assert "TRAINING" in enums and "LOOP_START" in enums


def test_el008_flags_uncalled_service_method():
    source = textwrap.dedent(EL008_CLIENT) + textwrap.dedent("""
        SERVICES = {
            "elasticdl_tpu.Master": {
                "get_task": (pb.GetTaskRequest, pb.GetTaskResponse),
                "dead_rpc": (pb.Empty, pb.Empty),
            },
        }

        class MasterServicer:
            def get_task(self, request, _context=None):
                return request

            def dead_rpc(self, request, _context=None):
                return request
    """)
    findings = [f for f in check_source(source)
                if f.rule == "EL008"]
    assert any("dead_rpc has no client stub caller" in f.message
               for f in findings)
    assert not any("get_task has no client" in f.message
                   for f in findings)


def test_el008_sees_stub_aliased_through_local():
    """The snapshot-under-lock idiom (master_client.py): the stub is
    read into a LOCAL under the refresh lock and the bound method is
    passed to the retry wrapper — the alias must keep its stub type so
    the call still registers as this service method's caller (and its
    request type still conformance-checks)."""
    source = textwrap.dedent("""
        import threading

        from elasticdl_tpu.proto import elastic_pb2 as pb
        from elasticdl_tpu.proto.rpc import MasterStub

        SERVICES = {
            "elasticdl_tpu.Master": {
                "get_task": (pb.GetTaskRequest, pb.GetTaskResponse),
            },
        }

        class MasterServicer:
            def get_task(self, request, _context=None):
                return request

        class Client:
            def __init__(self, channel):
                self._refresh_lock = threading.Lock()
                self._stub = MasterStub(channel)

            def _call(self, rpc_fn, request):
                return rpc_fn(request)

            def get_task(self):
                req = pb.GetTaskRequest(worker_id=3)
                with self._refresh_lock:
                    stub = self._stub
                return self._call(stub.get_task, req)

            def wrong_request(self):
                req = pb.ReportVersionRequest(model_version=1)
                with self._refresh_lock:
                    stub = self._stub
                return self._call(stub.get_task, req)
    """)
    findings = [f for f in check_source(source) if f.rule == "EL008"]
    assert not any("get_task has no client stub caller" in f.message
                   for f in findings)
    assert any("registers request type GetTaskRequest" in f.message
               and ".wrong_request" in f.symbol for f in findings)


# -- tracer lock-order edges --------------------------------------------


def test_tracer_confirms_seeded_abba_at_runtime():
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import fixture_abba

    alpha, beta = fixture_abba.build_pair()
    tracer = LockDisciplineTracer()
    alpha._lock = tracer.register_lock(alpha._lock, "LedgerAlpha._lock")
    beta._lock = tracer.register_lock(beta._lock, "LedgerBeta._lock")
    fixture_abba.drive_abba_sequentially(alpha, beta)
    assert tracer.lock_order_edges() == {
        ("LedgerAlpha._lock", "LedgerBeta._lock"),
        ("LedgerBeta._lock", "LedgerAlpha._lock"),
    }
    cycles = tracer.order_violations()
    assert cycles, "runtime ABBA cycle not detected"
    try:
        tracer.assert_ordered()
    except AssertionError as e:
        assert "LedgerAlpha._lock" in str(e)
    else:
        raise AssertionError("assert_ordered did not raise")


def test_tracer_order_edges_confirm_static_cycle():
    """The merge path: static EL005 graph + observed runtime edges —
    the seeded cycle is CONFIRMED (every edge actually executed)."""
    import ast as ast_mod

    from tools.elastic_lint import lock_graph as lg
    from tools.elastic_lint import program as pm

    sys.path.insert(0, os.path.join(REPO, "tests"))
    import fixture_abba

    source = _fixture_source(ABBA_FIXTURE)
    summary = pm.summarize_module(
        ast_mod.parse(source), source, "tests/fixture_abba.py")
    prog = pm.Program([summary])
    graph = lg.build_graph(prog)
    assert graph.cycles() and not graph.confirmed_cycles()

    alpha, beta = fixture_abba.build_pair()
    tracer = LockDisciplineTracer()
    prefix = "tests.fixture_abba."
    alpha._lock = tracer.register_lock(
        alpha._lock, prefix + "LedgerAlpha._lock")
    beta._lock = tracer.register_lock(
        beta._lock, prefix + "LedgerBeta._lock")
    fixture_abba.drive_abba_sequentially(alpha, beta)
    graph.merge_observed(tracer.lock_order_edges())
    assert graph.confirmed_cycles() == graph.cycles()


def test_tracer_quiet_on_clean_ordering():
    sys.path.insert(0, os.path.join(REPO, "tests"))
    import fixture_lock_order_clean as clean

    north, south = clean.build_pair()
    tracer = LockDisciplineTracer()
    north._lock = tracer.register_lock(north._lock, "North._lock")
    south._lock = tracer.register_lock(south._lock, "South._lock")
    clean.drive_sequentially(north, south)
    assert tracer.lock_order_edges() == {("North._lock", "South._lock")}
    tracer.assert_ordered()  # one-directional: no cycle


# -- baseline hygiene ----------------------------------------------------


def test_missing_explicit_baseline_is_hard_error(tmp_path):
    from tools.elastic_lint.suppressions import load_baseline

    try:
        load_baseline(str(tmp_path / "nope.txt"))
    except FileNotFoundError as e:
        assert "does not exist" in str(e)
    else:
        raise AssertionError("missing baseline did not raise")
    assert load_baseline(None) == set()


def test_stale_baseline_entry_fails_the_run(tmp_path):
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "EL001 elasticdl_tpu/no/such/file.py Gone.method.attr "
        "-- obsolete\n")
    findings = run_paths(
        [os.path.join(REPO, "tools", "elastic_lint")],
        baseline_path=str(baseline),
    )
    stale = [f for f in findings if f.rule == "ELSTALE"]
    assert stale, "zombie baseline entry not reported"
    assert "Gone.method.attr" in stale[0].symbol


def test_baseline_entries_outside_scan_scope_are_left_alone(tmp_path):
    """A partial-tree run must not flag the rest of the baseline."""
    baseline = tmp_path / "baseline.txt"
    baseline.write_text(
        "EL001 elasticdl_tpu/ps/servicer.py "
        "PserverServicer.pull_embedding_vectors.counters -- real\n")
    findings = run_paths(
        [os.path.join(REPO, "tools", "elastic_lint")],
        baseline_path=str(baseline),
    )
    assert not [f for f in findings if f.rule == "ELSTALE"]


# -- artifacts & parallelism --------------------------------------------


def test_lock_graph_artifact_produced_and_acyclic():
    """CI artifact contract: the lint gate emits the EL005 lock-order
    graph; its non-baselined subgraph must be acyclic (a baselined
    cycle would carry ``baselined: true`` and a justification in
    baseline.txt)."""
    import json

    artifact = os.path.join(REPO, "artifacts", "lock_graph.json")
    findings = run_paths(
        [os.path.join(REPO, "elasticdl_tpu"),
         os.path.join(REPO, "tools")],
        baseline_path=DEFAULT_BASELINE,
        graph_out=artifact,
    )
    assert not [f for f in findings if f.rule == "EL005"]
    assert os.path.isfile(artifact)
    with open(artifact, encoding="utf-8") as f:
        data = json.load(f)
    assert data["nodes"], "graph artifact lost the repo's lock nodes"
    unbaselined = [c for c in data["cycles"] if not c["baselined"]]
    assert not unbaselined, (
        "non-baselined lock-order cycles: %s" % unbaselined)
    # the known cross-component edges are present (docs embed these)
    edges = {(e["src"], e["dst"]) for e in data["edges"]}
    assert (
        "elasticdl_tpu.ps.servicer.PserverServicer._lock",
        "elasticdl_tpu.ps.parameters.Parameters._lock",
    ) in edges
    # The EvaluationService -> TaskManager edge was ELIMINATED by the
    # journal work: create_evaluation_tasks now journals task records
    # (file I/O, EL006), so EvaluationService calls it outside its
    # lock behind a _creating reservation.  Its absence IS the fix —
    # if it reappears, a convoy (and a blocking-under-lock finding)
    # came back with it.
    assert (
        "elasticdl_tpu.master.evaluation_service.EvaluationService._lock",
        "elasticdl_tpu.master.task_manager.TaskManager._lock",
    ) not in edges


def test_parallel_jobs_match_serial_findings():
    from tools.elastic_lint import build_program

    target = [os.path.join(REPO, "elasticdl_tpu", "master")]
    serial, _ = build_program(target, jobs=1)
    parallel, _ = build_program(target, jobs=2)
    assert sorted(serial) == sorted(parallel)


# -- the repo gate ------------------------------------------------------


def test_repo_is_lint_clean():
    """Tier-1 enforcement: the repo must stay clean under the per-file
    rules (EL001-EL004/EL007) AND the whole-program rules (EL005
    lock-order, EL006 blocking-under-lock, EL008 RPC conformance),
    modulo the justified baseline — and every baseline entry must
    still match a live finding (ELSTALE).  Targets mirror
    scripts/lint.sh's auto-discovery: a new bench_*.py or script
    cannot dodge the gate."""
    import glob

    findings = run_paths(
        [os.path.join(REPO, "elasticdl_tpu"),
         os.path.join(REPO, "tools"),
         os.path.join(REPO, "scripts")]
        + sorted(glob.glob(os.path.join(REPO, "bench_*.py"))),
        baseline_path=DEFAULT_BASELINE,
        jobs=2,
    )
    assert not findings, "\n".join(
        "%s:%d: %s %s" % (f.path, f.line, f.rule, f.message)
        for f in findings)


# -- EL011 whole-program shared-state races -----------------------------


RACE_FIXTURE = os.path.join(REPO, "tests", "fixture_race.py")
RACE_CLEAN_FIXTURE = os.path.join(REPO, "tests",
                                  "fixture_race_clean.py")


def test_el011_flags_seeded_race_fixture():
    findings = [f for f in check_source(
        _fixture_source(RACE_FIXTURE), "tests/fixture_race.py")
        if f.rule == "EL011"]
    assert {f.symbol for f in findings} == {
        "RacyTelemetryHub._total_reports",
        "RacyTelemetryHub._totals",
    }, "seeded two-root race not (fully) detected"
    # the finding anchors at the write and carries BOTH witness chains
    counter = next(f for f in findings
                   if f.symbol.endswith("_total_reports"))
    assert counter.line == 50
    assert "_flush_loop" in counter.message
    assert "_ingest" in counter.message
    assert " -> " in counter.message


def test_el011_quiet_on_guarded_fixture():
    """Same two roots, same attributes: RMWs under one common lock and
    an atomic-publication rebind must both stay silent."""
    findings = check_source(_fixture_source(RACE_CLEAN_FIXTURE),
                            "tests/fixture_race_clean.py")
    assert "EL011" not in {f.rule for f in findings}


EL011_READ_VS_WRITE = """
    import threading

    class Gauge:
        def __init__(self):
            self._lock = threading.Lock()
            self._value = 0
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._bump)
            self._t.start()

        def _bump(self):
            self._value += 1      # rmw with no lock

        def do_GET(self):         # stdlib-handler-shaped second root
            return self._value    # unguarded read
"""


def test_el011_write_vs_foreign_read_races():
    findings = [f for f in check_source(
        textwrap.dedent(EL011_READ_VS_WRITE)) if f.rule == "EL011"]
    assert findings and findings[0].symbol == "Gauge._value"
    assert "http" in findings[0].message  # handler root participates


def test_el011_common_lock_suppresses():
    source = textwrap.dedent(EL011_READ_VS_WRITE).replace(
        "        self._value += 1      # rmw with no lock",
        "        with self._lock:\n            self._value += 1",
    ).replace(
        "        return self._value    # unguarded read",
        "        with self._lock:\n            return self._value",
    )
    assert "EL011" not in {f.rule for f in check_source(source)}


def test_el011_queue_handoff_not_shared_state():
    source = """
        import queue
        import threading

        class Mailbox:
            def __init__(self):
                self._inbox = queue.Queue()
                self._t = None

            def start(self):
                self._t = threading.Thread(target=self._drain)
                self._t.start()

            def _drain(self):
                while True:
                    self._inbox.get()

            def do_GET(self):
                self._inbox.put("ping")
    """
    assert "EL011" not in rules_hit(source)


def test_thread_root_inventory_covers_every_tier():
    """Root discovery is the foundation EL011 stands on: losing a tier
    entrypoint silently shrinks the race search space.  Pin one-or-more
    roots per tier (master, PS, worker, serving server, router/fleet)
    and the aggregation daemon's honest blind spot."""
    from tools.elastic_lint import build_program
    from tools.elastic_lint import el011_shared_state as el011

    _, prog = build_program([os.path.join(REPO, "elasticdl_tpu")],
                            jobs=2)
    report = el011.build_report(prog)
    labels = {info["label"] for info in report.roots.values()}
    expected = {
        # master: gRPC plane (both servicer classes), daemon loops,
        # the status page's nested stdlib handler
        "rpc:elasticdl_tpu.master.servicer.MasterServicer.get_task",
        "rpc:elasticdl_tpu.master.scheduler.MultiTenantServicer.get_task",
        "thread:elasticdl_tpu.master.journal.JournalWriter._flush_loop",
        "thread:elasticdl_tpu.master.task_manager.TaskManager"
        "._watch_timeouts",
        "thread:elasticdl_tpu.master.worker_manager.WorkerManager"
        "._watch_worker",
        "thread:elasticdl_tpu.master.scheduler.ResizeController._loop",
        "thread:elasticdl_tpu.master.ps_manager.PSManager._watch",
        "http:elasticdl_tpu.master.status_server.Handler.do_GET",
        # PS: the RPC plane plus the master-watch reconnect daemon
        "rpc:elasticdl_tpu.ps.servicer.PserverServicer.push_gradients",
        "rpc:elasticdl_tpu.ps.servicer.PserverServicer"
        ".pull_embedding_vectors",
        "thread:elasticdl_tpu.ps.server.ParameterServer._watch_master",
        # worker: shard-index prefetcher and async checkpoint submit
        "thread:elasticdl_tpu.worker.data_shard_service"
        ".RecordIndexService._fill_indices",
        "submit:elasticdl_tpu.utils.checkpoint.CheckpointSaver.save",
        # serving server: batcher executor, reload scanner/warmer,
        # nested HTTP handler
        "thread:elasticdl_tpu.serving.batcher.ModelBatcher._run",
        "thread:elasticdl_tpu.serving.server.ModelEndpoint"
        "._scan_and_swap",
        "thread:elasticdl_tpu.serving.server.ModelEndpoint"
        "._prepare_worker",
        "http:elasticdl_tpu.serving.server.Handler.do_GET",
        "http:elasticdl_tpu.serving.server.Handler.do_POST",
        # router + fleet: rollout loop, autoscaler, health prober
        "http:elasticdl_tpu.serving.router.Handler.do_POST",
        "thread:elasticdl_tpu.serving.router.Router._rollout_loop",
        "thread:elasticdl_tpu.serving.fleet.FleetAutoscaler._run",
        "thread:elasticdl_tpu.serving.fleet.HealthProber._run",
    }
    missing = expected - labels
    assert not missing, "thread roots lost: %s" % sorted(missing)
    # The aggregation daemon publishes from its MAIN loop; its only
    # spawn is a nested SIGTERM closure the resolver cannot follow.
    # It must surface in the opaque list, not vanish.
    assert any(kind == "signal"
               and path.endswith("aggregation/main.py")
               for kind, path, _line in report.opaque_spawns)


def test_el011_baseline_suppresses_and_elstale_guards(tmp_path):
    """The PS hot-path entries use class-granular Class.attr symbols;
    a live match suppresses, a dead one is a hard ELSTALE error —
    same zombie-entry hygiene the method-granular rules get."""
    live = tmp_path / "live.txt"
    live.write_text(
        "EL011 tests/fixture_race.py RacyTelemetryHub._total_reports"
        " -- seeded\n"
        "EL011 tests/fixture_race.py RacyTelemetryHub._totals"
        " -- seeded\n")
    assert run_paths([RACE_FIXTURE], baseline_path=str(live)) == []

    dead = tmp_path / "dead.txt"
    dead.write_text(
        "EL011 tests/fixture_race.py RacyTelemetryHub._gone"
        " -- obsolete\n")
    findings = run_paths([RACE_FIXTURE], baseline_path=str(dead))
    stale = [f for f in findings if f.rule == "ELSTALE"]
    assert stale and "RacyTelemetryHub._gone" in stale[0].symbol


def test_races_artifact_names_roots_and_ps_hot_path():
    """CI artifact contract for --races-out: the matrix names every
    discovered root, the two baselined PS hot-path races (and only
    those), and keeps guarded attrs visible as non-racy rows."""
    import json

    artifact = os.path.join(REPO, "artifacts", "races.json")
    run_paths([os.path.join(REPO, "elasticdl_tpu")],
              baseline_path=DEFAULT_BASELINE,
              races_out=artifact)
    assert os.path.isfile(artifact)
    with open(artifact, encoding="utf-8") as f:
        data = json.load(f)
    labels = {r["label"] for r in data["roots"]}
    assert ("rpc:elasticdl_tpu.ps.servicer.PserverServicer"
            ".pull_embedding_vectors") in labels
    assert {r["attr"] for r in data["races"]} == {
        "elasticdl_tpu.ps.servicer.PserverServicer.counters",
        "elasticdl_tpu.ps.servicer.PserverServicer._params",
    }
    # guarded shared state stays in the matrix, marked clean
    doing = data["attrs"][
        "elasticdl_tpu.master.task_manager.TaskManager._doing"]
    assert not doing["racy"]
    assert any(per_root["guards"]
               for per_root in doing["roots"].values())
    # opaque spawn sites are listed, not silently dropped
    assert any(s["kind"] == "signal" for s in data["opaque_spawns"])


# -- --changed scoping ---------------------------------------------------


def test_import_closure_pulls_reverse_importers(tmp_path):
    from tools.elastic_lint import import_closure

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "base.py").write_text("X = 1\n")
    (pkg / "mid.py").write_text("from pkg import base\n")
    (pkg / "top.py").write_text("from . import mid\n")
    (pkg / "loner.py").write_text("Y = 2\n")
    files = ["pkg/__init__.py", "pkg/base.py", "pkg/mid.py",
             "pkg/top.py", "pkg/loner.py"]
    scoped = import_closure({"pkg/base.py"}, files, str(tmp_path))
    assert scoped == {"pkg/base.py", "pkg/mid.py", "pkg/top.py"}
    # a change outside the lint target set scopes to nothing
    assert import_closure({"docs/conf.py"}, files, str(tmp_path)) == set()


def test_changed_scope_walks_git_and_closure(tmp_path):
    import subprocess

    from tools.elastic_lint import changed_scope

    def git(*args):
        subprocess.run(["git", "-c", "user.email=t@t",
                        "-c", "user.name=t"] + list(args),
                       cwd=str(tmp_path), check=True,
                       capture_output=True)

    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "base.py").write_text("X = 1\n")
    (pkg / "mid.py").write_text("from pkg import base\n")
    (pkg / "loner.py").write_text("Y = 2\n")
    git("init", "-q")
    git("add", ".")
    git("commit", "-qm", "seed")
    scoped, changed = changed_scope([str(pkg)],
                                    repo_root=str(tmp_path))
    assert scoped == [] and changed == set()
    (pkg / "base.py").write_text("X = 2\n")
    scoped, changed = changed_scope([str(pkg)],
                                    repo_root=str(tmp_path))
    assert changed == {"pkg/base.py"}
    assert [os.path.basename(p) for p in scoped] == [
        "base.py", "mid.py"]
