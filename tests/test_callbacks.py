import json
import os

import numpy as np

from elasticdl_tpu.models import mnist
from elasticdl_tpu.models.callbacks import (
    LearningRateScheduler,
    ModelExporter,
    load_export,
)
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer


def test_model_exporter_roundtrip(tmp_path):
    spec = mnist.model_spec()
    trainer = CollectiveTrainer(spec, batch_size=8)
    xs, ys = mnist.synthetic_data(n=8)
    trainer.train_minibatch(xs, ys)
    export_dir = str(tmp_path / "export")
    ModelExporter(export_dir, model_name="mnist").on_train_end(trainer)
    assert os.path.exists(os.path.join(export_dir, "model.npz"))
    with open(os.path.join(export_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["model_name"] == "mnist"
    dense, embeddings = load_export(export_dir)
    live = trainer.export_parameters()
    assert set(dense) == set(live)
    for k in live:
        np.testing.assert_array_equal(dense[k], live[k])


def test_model_exporter_merges_ps_checkpoint(tmp_path):
    ckpt = CheckpointSaver(str(tmp_path / "ckpt"))
    ckpt.save(
        5,
        dense={"ps_only/w": np.ones(3, np.float32)},
        embeddings={"table": (np.array([1, 2]),
                              np.ones((2, 4), np.float32))},
    )
    spec = mnist.model_spec()
    trainer = CollectiveTrainer(spec, batch_size=8)
    export_dir = str(tmp_path / "export")
    ModelExporter(
        export_dir, checkpoint_dir=str(tmp_path / "ckpt")
    ).on_train_end(trainer)
    dense, embeddings = load_export(export_dir)
    assert "ps_only/w" in dense
    assert "table" in embeddings
    ids, values = embeddings["table"]
    assert sorted(ids.tolist()) == [1, 2]


def test_model_exporter_skips_stale_checkpoint_dense(tmp_path):
    """A checkpoint OLDER than the trainer's train-end params must not
    override matching dense weights (ADVICE r3: a collective trainer's
    last checkpoint can lag the final step); PS-side-only names still
    merge in."""
    spec = mnist.model_spec()
    trainer = CollectiveTrainer(spec, batch_size=8)
    xs, ys = mnist.synthetic_data(n=8)
    trainer.train_minibatch(xs, ys)
    live = dict(trainer.export_parameters())
    name = sorted(live)[0]
    assert trainer.version > 0
    ckpt = CheckpointSaver(str(tmp_path / "ckpt"))
    ckpt.save(
        0,  # older than trainer.version
        dense={name: np.zeros_like(live[name])},
        embeddings={},
    )
    export_dir = str(tmp_path / "export")
    ModelExporter(
        export_dir, checkpoint_dir=str(tmp_path / "ckpt")
    ).on_train_end(trainer)
    dense, _ = load_export(export_dir)
    np.testing.assert_array_equal(dense[name], live[name])  # not zeros

    # ... and a checkpoint at/after the trainer's version IS authoritative
    ckpt.save(trainer.version,
              dense={name: np.zeros_like(live[name])}, embeddings={})
    ModelExporter(
        str(tmp_path / "export2"), checkpoint_dir=str(tmp_path / "ckpt")
    ).on_train_end(trainer)
    dense2, _ = load_export(str(tmp_path / "export2"))
    np.testing.assert_array_equal(dense2[name],
                                  np.zeros_like(live[name]))


def test_lr_scheduler_sets_ps_trainer_lr():
    class FakeTrainer:
        version = 100
        _learning_rate = 0.0

    scheduler = LearningRateScheduler(
        lambda version: 0.1 if version < 50 else 0.01
    )
    trainer = FakeTrainer()
    lr = scheduler.on_train_batch_begin(trainer)
    assert lr == 0.01
    assert trainer._learning_rate == 0.01
