"""Shared test helpers (plain module, not conftest — see pytest's
import-mode notes on importing conftest directly)."""

import time


def wait_until(cond, timeout=10.0, interval=0.1):
    """Poll helper shared by the fault-tolerance drills."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False
