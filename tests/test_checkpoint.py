import os

import numpy as np

from elasticdl_tpu.utils.checkpoint import CheckpointSaver


def test_save_load_roundtrip(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    dense = {"a/w": np.random.rand(3, 3).astype(np.float32),
             "b/w": np.random.rand(2,).astype(np.float32)}
    emb = {"t": (np.array([1, 5, 9]), np.random.rand(3, 4).astype(np.float32))}
    saver.save(10, dense=dense, embeddings=emb, num_shards=3)
    d2, e2, v = saver.load()
    assert v == 10
    for k in dense:
        np.testing.assert_array_equal(d2[k], dense[k])
    ids, vals = e2["t"]
    order = np.argsort(ids)
    np.testing.assert_array_equal(ids[order], [1, 5, 9])


def test_validity_check_rejects_torn_writes(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    saver.save(1, dense={"w": np.zeros(2)}, num_shards=2)
    assert saver.is_valid_version(1)
    os.remove(os.path.join(str(tmp_path), "version-1",
                           "variables-1-of-2.ckpt"))
    assert not saver.is_valid_version(1)
    assert saver.versions() == []


def test_gc_keeps_max_versions(tmp_path):
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    for v in range(5):
        saver.save(v, dense={"w": np.full(2, v, np.float32)})
    assert saver.versions() == [3, 4]
    _, _, latest = saver.load()
    assert latest == 4


def test_reroute_shard_counts(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    dense = {"p%d" % i: np.full(2, i, np.float32) for i in range(8)}
    emb = {"t": (np.arange(10), np.arange(40).reshape(10, 4).astype(
        np.float32))}
    saver.save(0, dense=dense, embeddings=emb, num_shards=4)
    # Re-read as if we now run 3 PS shards.
    all_dense = {}
    all_ids = []
    for i in range(3):
        d, e, _ = saver.load_shard(0, i, 3)
        all_dense.update(d)
        all_ids.extend(e["t"][0].tolist())
    assert set(all_dense) == set(dense)
    assert sorted(all_ids) == list(range(10))
