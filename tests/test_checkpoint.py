import os

import numpy as np

from elasticdl_tpu.utils.checkpoint import CheckpointSaver


def test_save_load_roundtrip(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    dense = {"a/w": np.random.rand(3, 3).astype(np.float32),
             "b/w": np.random.rand(2,).astype(np.float32)}
    emb = {"t": (np.array([1, 5, 9]), np.random.rand(3, 4).astype(np.float32))}
    saver.save(10, dense=dense, embeddings=emb, num_shards=3)
    d2, e2, v = saver.load()
    assert v == 10
    for k in dense:
        np.testing.assert_array_equal(d2[k], dense[k])
    ids, vals = e2["t"]
    order = np.argsort(ids)
    np.testing.assert_array_equal(ids[order], [1, 5, 9])


def test_validity_check_rejects_torn_writes(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    saver.save(1, dense={"w": np.zeros(2)}, num_shards=2)
    assert saver.is_valid_version(1)
    os.remove(os.path.join(str(tmp_path), "version-1",
                           "variables-1-of-2.ckpt"))
    assert not saver.is_valid_version(1)
    assert saver.versions() == []


def test_gc_keeps_max_versions(tmp_path):
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    for v in range(5):
        saver.save(v, dense={"w": np.full(2, v, np.float32)})
    assert saver.versions() == [3, 4]
    _, _, latest = saver.load()
    assert latest == 4


def test_reroute_shard_counts(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    dense = {"p%d" % i: np.full(2, i, np.float32) for i in range(8)}
    emb = {"t": (np.arange(10), np.arange(40).reshape(10, 4).astype(
        np.float32))}
    saver.save(0, dense=dense, embeddings=emb, num_shards=4)
    # Re-read as if we now run 3 PS shards.
    all_dense = {}
    all_ids = []
    for i in range(3):
        d, e, _ = saver.load_shard(0, i, 3)
        all_dense.update(d)
        all_ids.extend(e["t"][0].tolist())
    assert set(all_dense) == set(dense)
    assert sorted(all_ids) == list(range(10))


def test_drifted_shard_set_refused_loudly(tmp_path):
    """Coordinated restore (docs/ps_recovery.md): a directory holding
    only drifted per-shard files — no label complete across the shard
    set — REFUSES to restore rather than silently handing shard 0 a
    version-100 slice and shard 1 a version-97 slice of one dense
    model."""
    import pytest

    saver = CheckpointSaver(str(tmp_path))
    saver.save_shard(100, 0, 2, dense={"a": np.full(2, 7, np.float32)})
    saver.save_shard(97, 1, 2, dense={"b": np.full(2, 9, np.float32)})
    assert saver.versions() == []  # no fully-valid version anywhere
    for shard in range(2):
        with pytest.raises(FileNotFoundError, match="mixed-version"):
            saver.load_shard(None, shard, 2)


def test_per_shard_gc_prunes_torn_dirs(tmp_path):
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    # Shard 0 checkpoints at drifting labels; shard 1 never shows up.
    for v in (10, 20, 30, 40):
        saver.save_shard(v, 0, 2, dense={"a": np.zeros(1, np.float32)})
    assert saver.shard_versions(0, 2) == [30, 40]
    leftover = sorted(os.listdir(str(tmp_path)))
    assert leftover == ["version-30", "version-40"]


def test_optimizer_slots_route_with_parent_param(tmp_path):
    """optslot/<param>@<slot> entries land on the shard that owns <param>
    after a shard-count change; optslot/__step__ replicates everywhere."""
    saver = CheckpointSaver(str(tmp_path))
    dense = {"p%d" % i: np.full(2, i, np.float32) for i in range(6)}
    for i in range(6):
        dense["optslot/p%d@m" % i] = np.full(2, 100 + i, np.float32)
    dense["optslot/__step__"] = np.array([42], np.int64)
    saver.save(0, dense=dense, num_shards=2)
    for shard in range(3):  # re-read with a different shard count
        d, _, _ = saver.load_shard(0, shard, 3)
        assert int(d["optslot/__step__"][0]) == 42
        for k in d:
            if k.startswith("optslot/") and k != "optslot/__step__":
                parent = k[len("optslot/"):].rsplit("@", 1)[0]
                assert parent in d, (
                    "slot %s landed on a shard without its param" % k
                )


def test_restore_uses_committed_label_not_newer_shard_file(tmp_path):
    """Every shard restores the newest COMMITTED (fully-valid) label —
    a lone shard's newer uncommitted file is part of no consistent cut
    and must not pull that one shard ahead of its siblings."""
    saver = CheckpointSaver(str(tmp_path))
    saver.save(100, dense={"a": np.full(1, 1, np.float32),
                           "b": np.full(1, 1, np.float32)}, num_shards=2)
    # Later, drifted per-shard writes (no complete version forms).
    saver.save_shard(150, 0, 2, dense={"a": np.full(1, 5, np.float32)})
    merged = {}
    for shard in range(2):
        d, _, v = saver.load_shard(None, shard, 2)
        assert v == 100
        merged.update(d)
    assert merged["a"][0] == 1 and merged["b"][0] == 1
    # The resume math the master uses agrees with what restores.
    assert saver.latest_resumable_version(2) == 100


def test_gc_never_tears_a_full_version(tmp_path):
    """Per-shard GC must not delete this shard's file out of a surviving
    fully-valid version (would break shard-count-change restores)."""
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    saver.save(100, dense={"a": np.zeros(1, np.float32),
                           "b": np.zeros(1, np.float32)}, num_shards=2)
    for v in (110, 120, 130, 140):
        saver.save_shard(v, 0, 2, dense={"a": np.zeros(1, np.float32)})
    assert saver.is_valid_version(100)  # survived shard-0 churn
    # A 3-shard relayout can still reroute from version-100.
    d, _, v = saver.load_shard(None, 0, 3)
    assert v == 100


def test_resize_leftovers_get_swept_and_label_reuse_validates(tmp_path):
    """Old-layout files don't permanently poison labels, and stale-layout
    dirs older than a complete new-layout version get swept."""
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    saver.save_shard(50, 0, 3, dense={"x": np.zeros(1, np.float32)})  # torn of-3
    # Resized to 2 shards; label 60 completes under the new layout.
    saver.save_shard(60, 0, 2, dense={"a": np.zeros(1, np.float32)})
    saver.save_shard(60, 1, 2, dense={"b": np.zeros(1, np.float32)})
    assert saver.is_valid_version(60)
    # One more write triggers GC; the torn of-3 dir is swept.
    saver.save_shard(70, 0, 2, dense={"a": np.zeros(1, np.float32)})
    assert not os.path.isdir(os.path.join(str(tmp_path), "version-50"))
    # A label holding both an old-layout leftover and a complete new
    # layout still validates.
    saver.save_shard(80, 1, 3, dense={"x": np.zeros(1, np.float32)})
    saver.save_shard(80, 0, 2, dense={"a": np.zeros(1, np.float32)})
    saver.save_shard(80, 1, 2, dense={"b": np.zeros(1, np.float32)})
    assert saver.is_valid_version(80)


def test_step_counter_merges_by_max_across_drifted_shards(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    saver.save_shard(
        10, 0, 2, dense={"optslot/__step__": np.array([5000], np.int64)}
    )
    saver.save_shard(
        10, 1, 2, dense={"optslot/__step__": np.array([200], np.int64)}
    )
    d, _, _ = saver.load(10)
    assert int(d["optslot/__step__"][0]) == 5000
