import os

import numpy as np

from elasticdl_tpu.utils.checkpoint import CheckpointSaver


def test_save_load_roundtrip(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    dense = {"a/w": np.random.rand(3, 3).astype(np.float32),
             "b/w": np.random.rand(2,).astype(np.float32)}
    emb = {"t": (np.array([1, 5, 9]), np.random.rand(3, 4).astype(np.float32))}
    saver.save(10, dense=dense, embeddings=emb, num_shards=3)
    d2, e2, v = saver.load()
    assert v == 10
    for k in dense:
        np.testing.assert_array_equal(d2[k], dense[k])
    ids, vals = e2["t"]
    order = np.argsort(ids)
    np.testing.assert_array_equal(ids[order], [1, 5, 9])


def test_validity_check_rejects_torn_writes(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    saver.save(1, dense={"w": np.zeros(2)}, num_shards=2)
    assert saver.is_valid_version(1)
    os.remove(os.path.join(str(tmp_path), "version-1",
                           "variables-1-of-2.ckpt"))
    assert not saver.is_valid_version(1)
    assert saver.versions() == []


def test_gc_keeps_max_versions(tmp_path):
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    for v in range(5):
        saver.save(v, dense={"w": np.full(2, v, np.float32)})
    assert saver.versions() == [3, 4]
    _, _, latest = saver.load()
    assert latest == 4


def test_reroute_shard_counts(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    dense = {"p%d" % i: np.full(2, i, np.float32) for i in range(8)}
    emb = {"t": (np.arange(10), np.arange(40).reshape(10, 4).astype(
        np.float32))}
    saver.save(0, dense=dense, embeddings=emb, num_shards=4)
    # Re-read as if we now run 3 PS shards.
    all_dense = {}
    all_ids = []
    for i in range(3):
        d, e, _ = saver.load_shard(0, i, 3)
        all_dense.update(d)
        all_ids.extend(e["t"][0].tolist())
    assert set(all_dense) == set(dense)
    assert sorted(all_ids) == list(range(10))


def test_per_shard_fallback_when_versions_drift(tmp_path):
    """Shards checkpointing at drifting version labels stay restorable:
    load_shard(None, i, N) falls back to shard i's own newest file when no
    fully-valid version exists (ADVICE r1: torn dirs made zero checkpoints
    restorable)."""
    saver = CheckpointSaver(str(tmp_path))
    saver.save_shard(100, 0, 2, dense={"a": np.full(2, 7, np.float32)})
    saver.save_shard(97, 1, 2, dense={"b": np.full(2, 9, np.float32)})
    assert saver.versions() == []  # no fully-valid version anywhere
    d0, _, v0 = saver.load_shard(None, 0, 2)
    d1, _, v1 = saver.load_shard(None, 1, 2)
    assert v0 == 100 and v1 == 97
    np.testing.assert_array_equal(d0["a"], np.full(2, 7, np.float32))
    np.testing.assert_array_equal(d1["b"], np.full(2, 9, np.float32))


def test_per_shard_gc_prunes_torn_dirs(tmp_path):
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    # Shard 0 checkpoints at drifting labels; shard 1 never shows up.
    for v in (10, 20, 30, 40):
        saver.save_shard(v, 0, 2, dense={"a": np.zeros(1, np.float32)})
    assert saver.shard_versions(0, 2) == [30, 40]
    leftover = sorted(os.listdir(str(tmp_path)))
    assert leftover == ["version-30", "version-40"]


def test_optimizer_slots_route_with_parent_param(tmp_path):
    """optslot/<param>@<slot> entries land on the shard that owns <param>
    after a shard-count change; optslot/__step__ replicates everywhere."""
    saver = CheckpointSaver(str(tmp_path))
    dense = {"p%d" % i: np.full(2, i, np.float32) for i in range(6)}
    for i in range(6):
        dense["optslot/p%d@m" % i] = np.full(2, 100 + i, np.float32)
    dense["optslot/__step__"] = np.array([42], np.int64)
    saver.save(0, dense=dense, num_shards=2)
    for shard in range(3):  # re-read with a different shard count
        d, _, _ = saver.load_shard(0, shard, 3)
        assert int(d["optslot/__step__"][0]) == 42
        for k in d:
            if k.startswith("optslot/") and k != "optslot/__step__":
                parent = k[len("optslot/"):].rsplit("@", 1)[0]
                assert parent in d, (
                    "slot %s landed on a shard without its param" % k
                )


def test_newer_per_shard_checkpoint_beats_old_full_version(tmp_path):
    """A fully-valid label from early in the job must not roll a shard
    back past its own later per-shard checkpoints."""
    saver = CheckpointSaver(str(tmp_path))
    saver.save(100, dense={"a": np.full(1, 1, np.float32),
                           "b": np.full(1, 1, np.float32)}, num_shards=2)
    # Later, drifted per-shard writes (no complete version forms).
    saver.save_shard(150, 0, 2, dense={"a": np.full(1, 5, np.float32)})
    d0, _, v0 = saver.load_shard(None, 0, 2)
    assert v0 == 150 and d0["a"][0] == 5
    # Shard 1 has nothing newer: falls back to the full version-100.
    _, _, v1 = saver.load_shard(None, 1, 2)
    assert v1 == 100


def test_gc_never_tears_a_full_version(tmp_path):
    """Per-shard GC must not delete this shard's file out of a surviving
    fully-valid version (would break shard-count-change restores)."""
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    saver.save(100, dense={"a": np.zeros(1, np.float32),
                           "b": np.zeros(1, np.float32)}, num_shards=2)
    for v in (110, 120, 130, 140):
        saver.save_shard(v, 0, 2, dense={"a": np.zeros(1, np.float32)})
    assert saver.is_valid_version(100)  # survived shard-0 churn
    # A 3-shard relayout can still reroute from version-100.
    d, _, v = saver.load_shard(None, 0, 3)
    assert v == 100


def test_resize_leftovers_get_swept_and_label_reuse_validates(tmp_path):
    """Old-layout files don't permanently poison labels, and stale-layout
    dirs older than a complete new-layout version get swept."""
    saver = CheckpointSaver(str(tmp_path), keep_max=2)
    saver.save_shard(50, 0, 3, dense={"x": np.zeros(1, np.float32)})  # torn of-3
    # Resized to 2 shards; label 60 completes under the new layout.
    saver.save_shard(60, 0, 2, dense={"a": np.zeros(1, np.float32)})
    saver.save_shard(60, 1, 2, dense={"b": np.zeros(1, np.float32)})
    assert saver.is_valid_version(60)
    # One more write triggers GC; the torn of-3 dir is swept.
    saver.save_shard(70, 0, 2, dense={"a": np.zeros(1, np.float32)})
    assert not os.path.isdir(os.path.join(str(tmp_path), "version-50"))
    # A label holding both an old-layout leftover and a complete new
    # layout still validates.
    saver.save_shard(80, 1, 3, dense={"x": np.zeros(1, np.float32)})
    saver.save_shard(80, 0, 2, dense={"a": np.zeros(1, np.float32)})
    saver.save_shard(80, 1, 2, dense={"b": np.zeros(1, np.float32)})
    assert saver.is_valid_version(80)


def test_step_counter_merges_by_max_across_drifted_shards(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    saver.save_shard(
        10, 0, 2, dense={"optslot/__step__": np.array([5000], np.int64)}
    )
    saver.save_shard(
        10, 1, 2, dense={"optslot/__step__": np.array([200], np.int64)}
    )
    d, _, _ = saver.load(10)
    assert int(d["optslot/__step__"][0]) == 5000
