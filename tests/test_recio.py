from elasticdl_tpu.data.recio import RecioReader, RecioWriter


def test_recio_roundtrip(tmp_path):
    path = str(tmp_path / "data.recio")
    records = [b"hello", b"", b"x" * 1000, b"last"]
    with RecioWriter(path) as w:
        for r in records:
            w.write(r)
    with RecioReader(path) as r:
        assert len(r) == 4
        assert [r.read(i) for i in range(4)] == records
        assert list(r.read_range(1, 3)) == records[1:3]
        assert list(r.read_range(2, 99)) == records[2:]
