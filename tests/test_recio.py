from elasticdl_tpu.data.recio import RecioReader, RecioWriter


def test_recio_roundtrip(tmp_path):
    path = str(tmp_path / "data.recio")
    records = [b"hello", b"", b"x" * 1000, b"last"]
    with RecioWriter(path) as w:
        for r in records:
            w.write(r)
    with RecioReader(path) as r:
        assert len(r) == 4
        assert [r.read(i) for i in range(4)] == records
        assert list(r.read_range(1, 3)) == records[1:3]
        assert list(r.read_range(2, 99)) == records[2:]


def test_readers_honor_shuffled_record_indices(tmp_path):
    """File readers iterate shard.record_indices when the TaskManager sets
    them (ADVICE r1: shuffle=True was silently a no-op for file readers)."""
    from elasticdl_tpu.data.reader import RecioDataReader, TextDataReader
    from elasticdl_tpu.master.task_manager import Shard, Task

    path = str(tmp_path / "data.recio")
    with RecioWriter(path) as w:
        for i in range(6):
            w.write(b"rec%d" % i)
    reader = RecioDataReader(str(tmp_path))
    order = [4, 1, 5, 2]
    task = Task(0, Shard(path, 1, 5, record_indices=order), 0)
    got = list(reader.read_records(task))
    assert got == [b"rec4", b"rec1", b"rec5", b"rec2"]

    csv_path = str(tmp_path / "data.csv")
    with open(csv_path, "w") as f:
        for i in range(6):
            f.write("row%d,%d\n" % (i, i))
    treader = TextDataReader(csv_path, records_per_task=3)
    task = Task(0, Shard(csv_path, 0, 4, record_indices=[3, 0, 2]), 0)
    got = list(treader.read_records(task))
    assert got == [["row3", "3"], ["row0", "0"], ["row2", "2"]]


def test_convert_csv_and_ctr_roundtrip(tmp_path):
    """Named converters (census/heart/frappe analogs) pack and decode."""
    import numpy as np

    from elasticdl_tpu.data.recio_gen import (
        convert_csv,
        convert_ctr,
        decode_record,
        decode_xy,
    )

    csv_path = tmp_path / "heart.csv"
    csv_path.write_text(
        "age,cp,thal,target\n63,typical,fixed,1\n37,atypical,normal,0\n"
    )
    files = convert_csv(str(csv_path), str(tmp_path / "heart_rec"),
                        skip_header=True)
    x, y = decode_xy(RecioReader(files[0]).read(0))
    assert x.shape == (3,) and x.dtype == np.float32
    assert int(y) == 1
    assert x[0] == 63.0  # numeric column passes through
    # categorical column hashed deterministically
    files2 = convert_csv(str(csv_path), str(tmp_path / "heart_rec2"),
                         skip_header=True)
    x2, _ = decode_xy(RecioReader(files2[0]).read(0))
    np.testing.assert_array_equal(x, x2)

    files = convert_ctr(str(tmp_path / "ctr_rec"), n=64,
                        records_per_file=32, vocab_size=100)
    assert len(files) == 2
    rec = decode_record(RecioReader(files[0]).read(0))
    assert set(rec) == {"dense", "ids", "y"}
    assert rec["ids"].dtype == np.int64


def test_convert_csv_categorical_label_and_bad_index(tmp_path):
    import numpy as np
    import pytest

    from elasticdl_tpu.data.recio_gen import convert_csv, decode_xy

    csv_path = tmp_path / "census.csv"
    csv_path.write_text("39,Private,<=50K\n50,Self-emp,>50K\n")
    files = convert_csv(str(csv_path), str(tmp_path / "rec"))
    labels = [
        int(decode_xy(RecioReader(files[0]).read(i))[1])
        for i in range(2)
    ]
    assert sorted(labels) == [0, 1]  # stable vocabulary ids
    with pytest.raises(ValueError, match="out of range"):
        convert_csv(str(csv_path), str(tmp_path / "rec2"),
                    label_column=10)


def test_convert_csv_edge_cases(tmp_path):
    import pytest

    from elasticdl_tpu.data.recio_gen import convert_csv

    ragged = tmp_path / "ragged.csv"
    ragged.write_text("1,2,0\n1,2\n")
    with pytest.raises(ValueError, match="ragged"):
        convert_csv(str(ragged), str(tmp_path / "r1"))

    mixed = tmp_path / "mixed.csv"
    mixed.write_text("1,2,0\n1,2,?\n")
    with pytest.raises(ValueError, match="mixes numeric"):
        convert_csv(str(mixed), str(tmp_path / "r2"))

    empty = tmp_path / "empty.csv"
    empty.write_text("")
    with pytest.raises(ValueError, match="no rows"):
        convert_csv(str(empty), str(tmp_path / "r3"),
                    skip_header=True)

    # literal "nan" feature buckets instead of poisoning with NaN
    import numpy as np

    from elasticdl_tpu.data.recio_gen import decode_xy

    nan_csv = tmp_path / "nan.csv"
    nan_csv.write_text("nan,1,0\n2.0,3,1\n")
    files = convert_csv(str(nan_csv), str(tmp_path / "r4"))
    x, _ = decode_xy(RecioReader(files[0]).read(0))
    assert np.isfinite(x).all()
