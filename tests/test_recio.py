from elasticdl_tpu.data.recio import RecioReader, RecioWriter


def test_recio_roundtrip(tmp_path):
    path = str(tmp_path / "data.recio")
    records = [b"hello", b"", b"x" * 1000, b"last"]
    with RecioWriter(path) as w:
        for r in records:
            w.write(r)
    with RecioReader(path) as r:
        assert len(r) == 4
        assert [r.read(i) for i in range(4)] == records
        assert list(r.read_range(1, 3)) == records[1:3]
        assert list(r.read_range(2, 99)) == records[2:]


def test_readers_honor_shuffled_record_indices(tmp_path):
    """File readers iterate shard.record_indices when the TaskManager sets
    them (ADVICE r1: shuffle=True was silently a no-op for file readers)."""
    from elasticdl_tpu.data.reader import RecioDataReader, TextDataReader
    from elasticdl_tpu.master.task_manager import Shard, Task

    path = str(tmp_path / "data.recio")
    with RecioWriter(path) as w:
        for i in range(6):
            w.write(b"rec%d" % i)
    reader = RecioDataReader(str(tmp_path))
    order = [4, 1, 5, 2]
    task = Task(0, Shard(path, 1, 5, record_indices=order), 0)
    got = list(reader.read_records(task))
    assert got == [b"rec4", b"rec1", b"rec5", b"rec2"]

    csv_path = str(tmp_path / "data.csv")
    with open(csv_path, "w") as f:
        for i in range(6):
            f.write("row%d,%d\n" % (i, i))
    treader = TextDataReader(csv_path, records_per_task=3)
    task = Task(0, Shard(csv_path, 0, 4, record_indices=[3, 0, 2]), 0)
    got = list(treader.read_records(task))
    assert got == [["row3", "3"], ["row0", "0"], ["row2", "2"]]
