"""Elastic controller: epoch-change handling, accum math, retry loop."""

import numpy as np
import pytest

from elasticdl_tpu.api.controller import (
    ElasticCollectiveController,
    compute_accum_steps,
)
from elasticdl_tpu.proto import elastic_pb2 as pb
from tests.test_utils import create_master, create_master_client


def test_compute_accum_steps_fixed_global_batch():
    # 8 microbatches globally over 3 workers: ranks 0,1 get 3, rank 2 gets 2
    assert compute_accum_steps(8, 0, 3) == 3
    assert compute_accum_steps(8, 1, 3) == 3
    assert compute_accum_steps(8, 2, 3) == 2
    assert compute_accum_steps(8, 0, 8) == 1
    assert compute_accum_steps(2, 5, 8) == 1  # never below 1


class FakeTrainer:
    def __init__(self):
        self.rebuilds = []
        self.accum = None

    def rebuild(self, mesh):
        self.rebuilds.append(mesh)

    def set_accum_steps(self, n):
        self.accum = n


def test_controller_reinits_on_epoch_change():
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8, rendezvous=True
    )
    try:
        mc = create_master_client(master, worker_id=0)
        trainer = FakeTrainer()
        controller = ElasticCollectiveController(
            mc, trainer, global_batch_num=8, check_secs=0.0,
            mesh_builder=lambda rank, world, coord: ("mesh", world),
        )
        calls = []

        @controller.elastic_run
        def step(x):
            calls.append(x)
            return x * 2

        with controller.scope():
            import time
            time.sleep(0.15)  # rendezvous grace
            assert step(1) == 2
            assert trainer.accum == 8  # world of 1 -> all microbatches local
            assert trainer.rebuilds == [("mesh", 1)]

            # second worker joins -> epoch bump -> rebuild with world=2
            mc2 = create_master_client(master, worker_id=1)
            mc2.report_train_loop_status(pb.LOOP_START)
            time.sleep(0.15)
            assert step(2) == 4
            assert trainer.rebuilds[-1] == ("mesh", 2)
            assert trainer.accum == 4
    finally:
        master.stop()


def test_controller_retries_on_step_failure():
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8, rendezvous=True
    )
    try:
        mc = create_master_client(master, worker_id=0)
        trainer = FakeTrainer()
        controller = ElasticCollectiveController(
            mc, trainer, global_batch_num=1, check_secs=0.0
        )
        state = {"fails": 2}

        @controller.elastic_run
        def flaky():
            if state["fails"] > 0:
                state["fails"] -= 1
                raise RuntimeError("collective timeout")
            return "ok"

        with controller.scope():
            import time
            time.sleep(0.15)
            assert flaky() == "ok"

        @controller.elastic_run
        def always_fails():
            raise RuntimeError("dead link")

        with pytest.raises(RuntimeError, match="re-rendezvous retries"):
            always_fails()
    finally:
        master.stop()


def test_step_check_cadence_is_step_counted():
    """check_steps=N: the rendezvous is polled every N wrapped calls —
    the SPMD-safe cadence (all members observe a new epoch at the same
    collective index), not wall-clock."""
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8,
        rendezvous=True,
    )
    try:
        mc = create_master_client(master, worker_id=0)
        trainer = FakeTrainer()
        controller = ElasticCollectiveController(
            mc, trainer, check_steps=3,
            mesh_builder=lambda r, w, c: ("mesh", w),
        )
        with controller.scope():
            import time
            time.sleep(0.15)
            controller.step_check()  # first call: world init
            assert trainer.rebuilds == [("mesh", 1)]
            # second worker joins; cadence says: no check for 2 calls
            mc2 = create_master_client(master, worker_id=1)
            mc2.report_train_loop_status(pb.LOOP_START)
            time.sleep(0.15)
            controller.step_check()
            controller.step_check()
            assert trainer.rebuilds == [("mesh", 1)]  # not yet
            controller.step_check()  # 3rd call since check -> poll
            assert trainer.rebuilds[-1] == ("mesh", 2)
    finally:
        master.stop()


def test_await_new_epoch_times_out_without_change():
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8,
        rendezvous=True,
    )
    try:
        mc = create_master_client(master, worker_id=0)
        controller = ElasticCollectiveController(
            mc, FakeTrainer(), check_secs=0.0)
        with controller.scope():
            import time
            time.sleep(0.15)
            controller.init_world_if_needed()
            t0 = time.monotonic()
            assert controller.await_new_epoch(timeout=0.5,
                                              poll_secs=0.05) is False
            assert time.monotonic() - t0 < 5.0
    finally:
        master.stop()


def test_leave_and_rejoin_world():
    """The idle-worker protocol: leave_world snapshots + exits, the
    master commits a smaller epoch; rejoin_world re-enters after
    LOOP_START and rebuilds — and the next step_check does NOT
    redundantly re-init (rejoin counts as the world init)."""
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8,
        rendezvous=True,
    )
    try:
        mc = create_master_client(master, worker_id=0)

        class SnapshotTrainer(FakeTrainer):
            def __init__(self):
                super().__init__()
                self.snapshots = 0

            def snapshot_to_host(self):
                self.snapshots += 1

        trainer = SnapshotTrainer()
        controller = ElasticCollectiveController(
            mc, trainer, check_steps=1,
            mesh_builder=lambda r, w, c: ("mesh", w),
        )
        import time

        with controller.scope():
            time.sleep(0.15)
            controller.step_check()
            assert trainer.rebuilds == [("mesh", 1)]
            controller.leave_world()
            assert trainer.snapshots >= 1
            mc.report_train_loop_status(pb.LOOP_END)
            time.sleep(0.15)
            # commits are lazy (inside get_comm_rank) — poke one
            rank, size, _, _ = master.rendezvous_server.get_comm_rank(
                "worker-0")
            assert (rank, size) == (-1, 0)
            mc.report_train_loop_status(pb.LOOP_START)
            controller.rejoin_world(timeout=10)
            assert trainer.rebuilds[-1] == ("mesh", 1)
            rebuilds_after_rejoin = len(trainer.rebuilds)
            controller.step_check()  # must NOT re-init the same epoch
            assert len(trainer.rebuilds) == rebuilds_after_rejoin
    finally:
        master.stop()


def test_zero1_snapshot_falls_back_to_fresh_moments(monkeypatch):
    """snapshot_to_host: params must survive a world change; ZeRO-1
    optimizer shards lost with a dead peer are re-initialized from
    params (the information loss a Horovod restart accepts when it
    reloads a checkpoint without slots)."""
    import numpy as np

    from elasticdl_tpu.models import mnist
    from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

    spec = mnist.model_spec()
    trainer = CollectiveTrainer(spec, batch_size=4)
    xs, ys = mnist.synthetic_data(n=4)
    trainer.train_minibatch(xs, ys)  # moments become non-zero

    from elasticdl_tpu.utils.pytree import to_numpy as real_to_numpy

    calls = {"n": 0}

    def flaky_to_numpy(tree):
        calls["n"] += 1
        if calls["n"] == 2:  # params succeed; opt state "sharded away"
            raise ValueError("array is sharded across processes")
        return real_to_numpy(tree)

    monkeypatch.setattr(
        "elasticdl_tpu.worker.collective_trainer.to_numpy",
        flaky_to_numpy,
    )
    trainer.snapshot_to_host()
    # params preserved; moments re-initialized (zeros)
    import jax

    opt_leaves = jax.tree_util.tree_leaves(trainer._opt_state)
    big = [leaf for leaf in opt_leaves if np.size(leaf) > 1]
    assert big and all(
        np.allclose(np.asarray(leaf), 0) for leaf in big
    )


def test_coordinator_factory_failure_defers_commit():
    """The coordination plane is stood up BEFORE the epoch publishes:
    a factory failure (port stolen between probe and bind) must NOT
    commit a new rendezvous_id pointing at the old address — the
    commit defers, re-arms the grace window, and succeeds on retry."""
    import time

    from elasticdl_tpu.master.rendezvous import RendezvousServer

    calls = {"n": 0}

    def flaky_factory(world_size):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("address in use")
        return "jaxsvc://localhost:%d" % (40000 + world_size)

    rdzv = RendezvousServer(grace_secs=0.05,
                            coordinator_factory=flaky_factory)
    rdzv.add_worker("w0")
    time.sleep(0.06)
    rank, size, epoch, addr = rdzv.get_comm_rank("w0")  # factory fails
    assert (rank, size, epoch, addr) == (-1, 0, 0, "")
    time.sleep(0.06)  # grace re-armed; retry succeeds
    rank, size, epoch, addr = rdzv.get_comm_rank("w0")
    assert (rank, size, epoch) == (0, 1, 1)
    assert addr == "jaxsvc://localhost:40001"
    assert calls["n"] == 2


class _ScriptedMC:
    """Master client returning a scripted get_comm_rank sequence (the
    last entry repeats)."""

    def __init__(self, responses):
        self._responses = list(responses)
        self.loop_statuses = []

    def get_comm_rank(self):
        class Res:
            pass

        res = Res()
        (res.rendezvous_id, res.rank_id, res.world_size,
         res.coordinator_addr) = (
            self._responses.pop(0) if len(self._responses) > 1
            else self._responses[0]
        )
        return res

    def report_train_loop_status(self, status):
        self.loop_statuses.append(status)


def test_await_new_epoch_never_reinits_as_nonmember():
    """ADVICE r5 low: a new epoch can commit WITHOUT this host (grace
    window batching); await_new_epoch must keep polling until rank >= 0
    instead of building a coordination client with process_id=-1."""
    mc = _ScriptedMC([
        (2, -1, 2, "jaxsvc://x:1"),  # epoch changed, we're not in it
        (2, -1, 2, "jaxsvc://x:1"),
        (3, 1, 3, "jaxsvc://x:2"),   # next epoch admits us
    ])
    trainer = FakeTrainer()
    built = []
    controller = ElasticCollectiveController(
        mc, trainer, global_batch_num=3,
        mesh_builder=lambda r, w, c: built.append((r, w)) or ("m", w),
    )
    controller._rendezvous.rendezvous_id = 1  # was a member of epoch 1
    controller._rendezvous.rank = 0
    assert controller.await_new_epoch(timeout=5.0, poll_secs=0.01)
    assert built == [(1, 3)], built  # never called with rank=-1
    assert trainer.rebuilds == [("m", 3)]


def test_step_check_skips_reinit_while_excluded():
    """The cadence path has the same guard: an epoch that excludes this
    host must not trigger _reinit_world (rank=-1) — it must DETACH
    (the old epoch's service gets reaped, and an attached client dies
    with it) and re-announce LOOP_START so the master re-admits us."""
    mc = _ScriptedMC([
        (1, 0, 1, ""),               # first init: world of 1
        (2, -1, 2, "jaxsvc://x:1"),  # bumped epoch excludes us
        (3, 0, 3, "jaxsvc://x:2"),   # re-admitted
    ])
    trainer = FakeTrainer()
    built = []
    controller = ElasticCollectiveController(
        mc, trainer, check_steps=1,
        mesh_builder=lambda r, w, c: built.append((r, w)) or ("m", w),
    )
    controller.step_check()          # init at world 1
    controller.step_check()          # excluded epoch: detach, no rebuild
    assert built == [(0, 1)], built  # no rebuild with rank=-1
    assert mc.loop_statuses == [pb.LOOP_START]  # re-announced ourselves
    controller.step_check()          # re-admitted: rebuild now
    assert built == [(0, 1), (0, 3)], built


def test_derive_reap_secs_tracks_check_cadence(monkeypatch):
    """ADVICE r5 medium: the old-epoch service must outlive the
    survivors' worst-case epoch discovery (check cadence + margin),
    not a fixed 30 s."""
    from elasticdl_tpu.parallel import distributed as dist

    monkeypatch.setenv("ELASTICDL_STEP_SECS_BOUND", "5.0")
    monkeypatch.setenv("ELASTICDL_COLLECTIVE_HEARTBEAT", "10")
    # step-count cadence: 8 steps * 5 s bound + 2*10 s margin
    assert dist.derive_reap_secs(check_steps=8) == 8 * 5.0 + 20.0
    # wall-clock cadence dominates when larger
    assert dist.derive_reap_secs(check_steps=2, check_secs=120.0) == 140.0
    # no cadence configured: the default check interval + margin
    assert dist.derive_reap_secs() == 20.0 + 20.0
    # the service default derives rather than hard-coding 30 s
    svc = dist.MasterCoordinationService()
    assert svc._reap_secs == dist.derive_reap_secs()
    assert dist.MasterCoordinationService(reap_secs=7.5)._reap_secs == 7.5
