"""Elastic controller: epoch-change handling, accum math, retry loop."""

import numpy as np
import pytest

from elasticdl_tpu.api.controller import (
    ElasticCollectiveController,
    compute_accum_steps,
)
from elasticdl_tpu.proto import elastic_pb2 as pb
from tests.test_utils import create_master, create_master_client


def test_compute_accum_steps_fixed_global_batch():
    # 8 microbatches globally over 3 workers: ranks 0,1 get 3, rank 2 gets 2
    assert compute_accum_steps(8, 0, 3) == 3
    assert compute_accum_steps(8, 1, 3) == 3
    assert compute_accum_steps(8, 2, 3) == 2
    assert compute_accum_steps(8, 0, 8) == 1
    assert compute_accum_steps(2, 5, 8) == 1  # never below 1


class FakeTrainer:
    def __init__(self):
        self.rebuilds = []
        self.accum = None

    def rebuild(self, mesh):
        self.rebuilds.append(mesh)

    def set_accum_steps(self, n):
        self.accum = n


def test_controller_reinits_on_epoch_change():
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8, rendezvous=True
    )
    try:
        mc = create_master_client(master, worker_id=0)
        trainer = FakeTrainer()
        controller = ElasticCollectiveController(
            mc, trainer, global_batch_num=8, check_secs=0.0,
            mesh_builder=lambda rank, world, coord: ("mesh", world),
        )
        calls = []

        @controller.elastic_run
        def step(x):
            calls.append(x)
            return x * 2

        with controller.scope():
            import time
            time.sleep(0.15)  # rendezvous grace
            assert step(1) == 2
            assert trainer.accum == 8  # world of 1 -> all microbatches local
            assert trainer.rebuilds == [("mesh", 1)]

            # second worker joins -> epoch bump -> rebuild with world=2
            mc2 = create_master_client(master, worker_id=1)
            mc2.report_train_loop_status(pb.LOOP_START)
            time.sleep(0.15)
            assert step(2) == 4
            assert trainer.rebuilds[-1] == ("mesh", 2)
            assert trainer.accum == 4
    finally:
        master.stop()


def test_controller_retries_on_step_failure():
    master = create_master(
        training_shards=[("f", 0, 8)], records_per_task=8, rendezvous=True
    )
    try:
        mc = create_master_client(master, worker_id=0)
        trainer = FakeTrainer()
        controller = ElasticCollectiveController(
            mc, trainer, global_batch_num=1, check_secs=0.0
        )
        state = {"fails": 2}

        @controller.elastic_run
        def flaky():
            if state["fails"] > 0:
                state["fails"] -= 1
                raise RuntimeError("collective timeout")
            return "ok"

        with controller.scope():
            import time
            time.sleep(0.15)
            assert flaky() == "ok"

        @controller.elastic_run
        def always_fails():
            raise RuntimeError("dead link")

        with pytest.raises(RuntimeError, match="re-rendezvous retries"):
            always_fails()
    finally:
        master.stop()
