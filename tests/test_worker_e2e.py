"""End-to-end: master-dispatched shards train MNIST via a real worker.

The integration harness pattern from the reference
(elasticdl/python/tests/test_utils.py:330-472): real TaskManager, real gRPC
master service, real Worker — one process, no cluster.
"""

import numpy as np
import pytest

from elasticdl_tpu.data.reader import ArrayDataReader
from elasticdl_tpu.models import mnist
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import metrics
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import create_master, create_master_client


@pytest.fixture(scope="module")
def dataset():
    return mnist.synthetic_data(n=256, seed=1)


def run_job(dataset, num_epochs=2, evaluation_steps=0):
    xs, ys = dataset
    reader = ArrayDataReader((xs, ys), records_per_shard=64)
    master = create_master(
        training_shards=reader.create_shards(),
        evaluation_shards=reader.create_shards() if evaluation_steps else None,
        records_per_task=64,
        num_epochs=num_epochs,
        evaluation_steps=evaluation_steps,
        metrics_factory=(
            (lambda: {"accuracy": metrics.Accuracy()})
            if evaluation_steps else None
        ),
    )
    try:
        mc = create_master_client(master)
        spec = mnist.model_spec(learning_rate=5e-3)
        trainer = CollectiveTrainer(
            spec, batch_size=32, master_client=mc,
            report_version_steps=2 if evaluation_steps else 0,
        )
        worker = Worker(mc, reader, spec, trainer, batch_size=32)
        worker.run()
        assert master.task_manager.finished()
        return master, trainer
    finally:
        master.stop()


def test_training_completes_all_tasks(dataset):
    master, trainer = run_job(dataset)
    counts = master.task_manager.counts()
    assert counts["completed"][pb.TRAINING] == 8  # 4 shards x 2 epochs
    assert counts["failed"][pb.TRAINING] == 0
    assert trainer.version == 16  # 2 batches per task


def test_training_learns(dataset):
    xs, ys = dataset
    _, trainer = run_job(dataset, num_epochs=4)
    correct, total = 0, 0
    for i in range(0, 128, 32):
        outputs, labels = trainer.evaluate_minibatch(
            xs[i : i + 32], ys[i : i + 32]
        )
        correct += (np.argmax(outputs, -1) == labels).sum()
        total += len(labels)
    accuracy = correct / total
    assert accuracy > 0.5, f"model did not learn (acc={accuracy})"


def test_permanent_task_failure_fails_the_job(dataset):
    # A job that "finishes" after dropping tasks must exit nonzero —
    # permanently-failed tasks are unprocessed data, not success.
    xs, ys = dataset
    reader = ArrayDataReader((xs, ys), records_per_shard=64)
    master = create_master(
        training_shards=reader.create_shards(), records_per_task=64,
    )
    try:
        tm = master.task_manager
        while True:
            task = tm.get(worker_id=0)
            if task is None:
                break
            tm.report(task.id, success=False, err_message="boom")
        assert sum(tm.counts()["failed"].values()) > 0
        master._poll_secs = 0.05
        assert master.run() == 1
    finally:
        master.stop()


def test_evaluation_service_runs(dataset):
    master, _ = run_job(dataset, num_epochs=2, evaluation_steps=4)
    assert master.evaluation_service.history, "no evaluation completed"


def test_worker_death_tasks_recovered(dataset):
    """Kill a worker mid-job; a second worker finishes everything."""
    xs, ys = dataset
    reader = ArrayDataReader((xs, ys), records_per_shard=64)
    master = create_master(
        training_shards=reader.create_shards(), records_per_task=64
    )
    try:
        spec = mnist.model_spec()

        mc1 = create_master_client(master, worker_id=1)
        # Worker 1 grabs a task and "dies" (never reports).
        t = mc1.get_task()
        assert t.id > 0
        master.task_manager.recover_tasks(1)

        mc2 = create_master_client(master, worker_id=2)
        trainer = CollectiveTrainer(spec, batch_size=32)
        worker = Worker(mc2, reader, spec, trainer, batch_size=32)
        worker.run()
        counts = master.task_manager.counts()
        assert master.task_manager.finished()
        assert counts["completed"][pb.TRAINING] == 4
    finally:
        master.stop()


@pytest.mark.slow
def test_predict_job_writes_outputs(tmp_path):
    """Train -> checkpoint -> predict: the managed predict job restores
    the checkpoint and writes one npz of predictions per worker."""
    import os
    import subprocess
    import sys

    import numpy as np

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTICDL_TPU_PLATFORM"] = "cpu"
    ckpt = str(tmp_path / "ckpt")
    base = [
        sys.executable, "-m", "elasticdl_tpu.master.main",
        "--model_zoo", "mnist", "--batch_size", "32",
        "--num_workers", "1", "--num_minibatches_per_task", "4",
        "--checkpoint_dir", ckpt,
    ]
    train = subprocess.run(
        base + ["--data_origin", "synthetic_mnist:256",
                "--checkpoint_steps", "4"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert train.returncode == 0, train.stderr[-2000:]

    outputs = str(tmp_path / "preds")
    predict = subprocess.run(
        base + ["--job_type", "predict",
                "--data_origin", "synthetic_mnist:96",
                "--prediction_outputs", outputs],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert predict.returncode == 0, predict.stderr[-2000:]
    files = [f for f in os.listdir(outputs) if f.endswith(".npz")]
    assert files, "no prediction outputs written"
    total = 0
    for f in files:
        with np.load(os.path.join(outputs, f)) as z:
            preds = z["predictions"]
            assert preds.shape[-1] == 10  # mnist logits
            total += preds.shape[0]
    assert total == 96


@pytest.mark.slow
def test_evaluate_job_reports_metrics(tmp_path):
    """Train -> checkpoint -> standalone evaluate job: metrics are
    aggregated and logged by the master's evaluation service."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTICDL_TPU_PLATFORM"] = "cpu"
    ckpt = str(tmp_path / "ckpt")
    base = [
        sys.executable, "-m", "elasticdl_tpu.master.main",
        "--model_zoo", "mnist", "--batch_size", "32",
        "--num_workers", "1", "--num_minibatches_per_task", "4",
        "--checkpoint_dir", ckpt,
    ]
    train = subprocess.run(
        base + ["--data_origin", "synthetic_mnist:256",
                "--checkpoint_steps", "4"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert train.returncode == 0, train.stderr[-2000:]
    ev = subprocess.run(
        base + ["--job_type", "evaluate",
                "--data_origin", "synthetic_mnist:96"],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert ev.returncode == 0, ev.stderr[-2000:]
    text = ev.stdout + ev.stderr
    assert "job finished" in text
    assert "accuracy" in text, text[-2000:]


@pytest.mark.slow
def test_managed_collective_two_workers_form_world():
    """Managed elastic AllReduce (SURVEY §2.12): a two-worker managed
    job with --distribution_strategy collective forms a REAL
    cross-process world through the master-hosted coordination plane —
    both worker processes join one 2-device world, train global
    batches in lockstep, survive the end-of-data membership change
    (the first worker to drain the queue leaves; the other re-forms
    and finishes), and the job completes with zero lost tasks."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTICDL_TPU_PLATFORM"] = "cpu"
    env["ELASTICDL_COLLECTIVE_HEARTBEAT"] = "5"
    proc = subprocess.run(
        [
            sys.executable, "-m", "elasticdl_tpu.master.main",
            "--model_zoo", "mnist", "--batch_size", "16",
            "--num_workers", "2", "--num_minibatches_per_task", "4",
            "--data_origin", "synthetic_mnist:1024",
            "--distribution_strategy", "collective",
        ],
        capture_output=True, text=True, env=env, timeout=420,
    )
    text = proc.stdout + proc.stderr
    assert proc.returncode == 0, text[-4000:]
    assert "job finished" in text
    assert "'failed': {0: 0" in text, text[-2000:]
    # Both workers client-only joined the same 2-process world.
    assert "collective world joined (client-only): rank 0 / 2" in text
    assert "collective world joined (client-only): rank 1 / 2" in text


@pytest.mark.slow
def test_graceful_preemption_checkpoints_before_exit(tmp_path):
    """SIGTERM mid-run (the preemptible-VM grace signal): the worker
    finishes its minibatch, saves a checkpoint (checkpoint_steps=0 —
    no periodic saves, so any checkpoint on disk came from the
    graceful path), exits 143, the manager classifies it as a
    preemption and relaunches, and the job finishes with zero lost
    tasks."""
    import os
    import signal as _signal
    import subprocess
    import sys
    import time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTICDL_TPU_PLATFORM"] = "cpu"
    ckpt = str(tmp_path / "ckpt")
    job = "graceful-preempt-drill"
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "elasticdl_tpu.master.main",
            "--job_name", job,
            "--model_zoo", "mnist", "--batch_size", "32",
            "--num_workers", "1", "--num_minibatches_per_task", "4",
            "--data_origin", "synthetic_mnist:4096", "--num_epochs", "2",
            "--checkpoint_dir", ckpt, "--checkpoint_steps", "0",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        deadline = time.time() + 120
        wpid = None
        while time.time() < deadline and wpid is None:
            out = subprocess.run(
                ["pgrep", "-f",
                 "elasticdl_tpu.worker.main.*%s" % job],
                capture_output=True, text=True,
            )
            pids = [int(p) for p in out.stdout.split()]
            if pids:
                wpid = pids[0]
            else:
                time.sleep(0.5)
        assert wpid, "worker never appeared"
        time.sleep(20)  # let it get into training
        os.kill(wpid, _signal.SIGTERM)
        out, _ = proc.communicate(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out[-4000:]
    assert "job finished" in out
    assert "'failed': {0: 0" in out, out[-2000:]
    assert "graceful preemption: saving checkpoint" in out
    # exit 143 classified as preemption -> relaunch, not failure
    assert "exited code=143 event=preempted" in out, out[-3000:]
    # With checkpoint_steps=0 the ONLY possible checkpoint is the
    # graceful-preemption one.
    assert os.path.isdir(ckpt) and any(
        name.startswith("version-") for name in os.listdir(ckpt)
    ), os.listdir(ckpt) if os.path.isdir(ckpt) else "no ckpt dir"


@pytest.mark.slow
def test_managed_collective_lora_finetune():
    """Elastic fine-tuning: the LoRA zoo entry under a managed
    2-worker collective world — multi_transform masking, the
    {base, lora} param tree, and snapshot_to_host all ride the
    cross-process global-batch path; zero lost tasks."""
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTICDL_TPU_PLATFORM"] = "cpu"
    env["ELASTICDL_COLLECTIVE_HEARTBEAT"] = "5"
    proc = subprocess.run(
        [
            sys.executable, "-m", "elasticdl_tpu.master.main",
            "--model_zoo", "lora",
            "--model_params",
            "rank=4;vocab_size=128;dim=32;num_heads=4;num_layers=2;"
            "seq_len=16;dtype=float32",
            "--data_origin", "synthetic_lm:512:16:128",
            "--batch_size", "8", "--num_workers", "2",
            "--num_minibatches_per_task", "4",
            "--distribution_strategy", "collective",
        ],
        capture_output=True, text=True, env=env, timeout=420,
    )
    text = proc.stdout + proc.stderr
    assert proc.returncode == 0, text[-4000:]
    assert "job finished" in text
    assert "'failed': {0: 0" in text, text[-2000:]
    assert "collective world joined (client-only): rank 0 / 2" in text
    assert "LoRA r=4" in text
