"""Percentile plane + SLO watchdog tests (ISSUE 14).

Covers utils/hist.py (fixed-bound streaming histograms, exact sparse-
delta merge, windowed view), the Timing integration behind every
phase mean, utils/slo.py (declarative rules, breach episodes,
/alertz), the SIGQUIT live flight-recorder dump, /profilez, the
master-side step-time aggregation + straggler detector fed by
piggybacked worker deltas, and the ResizeController's straggler
policy term.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.status_server import StatusServer
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import hist, slo, tracing
from elasticdl_tpu.utils.prom import to_prometheus
from elasticdl_tpu.utils.timing import Timing

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- hist.py -----------------------------------------------------------------

def test_bucket_bounds_are_frozen():
    """Cross-process exactness depends on every process agreeing on
    the boundary list — a change here must be deliberate (and bump
    DELTA_VERSION)."""
    assert len(hist.BUCKET_BOUNDS) == 22
    assert hist.BUCKET_BOUNDS[0] == pytest.approx(1e-5)
    assert hist.BUCKET_BOUNDS[-1] == pytest.approx(100.0)
    assert list(hist.BUCKET_BOUNDS) == sorted(hist.BUCKET_BOUNDS)
    assert hist.N_BUCKETS == 23


def test_observe_quantile_and_mean():
    h = hist.Histogram()
    for _ in range(90):
        h.observe(0.001)
    for _ in range(10):
        h.observe(0.5)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["sum"] == pytest.approx(90 * 0.001 + 10 * 0.5)
    p50 = hist.quantile(snap, 0.5)
    p99 = hist.quantile(snap, 0.99)
    assert p50 <= 0.001  # in the 0.001 bucket
    assert 0.1 <= p99 <= 1.0  # in the 0.5 bucket's range
    assert hist.mean(snap) == pytest.approx(snap["sum"] / 100)
    assert hist.quantile(hist.empty_snapshot(), 0.99) is None


def test_overflow_bucket_and_bulk_observe():
    h = hist.Histogram()
    h.observe(1e9, n=3)  # beyond the top bound -> overflow bucket
    snap = h.snapshot()
    assert snap["counts"][-1] == 3
    assert snap["count"] == 3
    # quantile caps at the top finite bound (the scraper convention)
    assert hist.quantile(snap, 0.5) == hist.BUCKET_BOUNDS[-1]


def test_sparse_delta_round_trip_is_exact():
    h = hist.Histogram()
    for v in (0.001, 0.002, 0.004, 0.1, 3.0):
        h.observe(v)
    first = h.snapshot()
    for v in (0.002, 0.002, 50.0):
        h.observe(v)
    second = h.snapshot()
    d1 = hist.delta(first, None)
    d2 = hist.delta(second, first)
    payload1 = hist.encode_deltas({"step_time": d1})
    payload2 = hist.encode_deltas({"step_time": d2})
    acc = hist.empty_snapshot()
    hist.merge_delta(acc, hist.decode_deltas(payload1)["step_time"])
    hist.merge_delta(acc, hist.decode_deltas(payload2)["step_time"])
    assert acc == second  # EXACT, bit-for-bit, including the float sum


def test_decode_rejects_garbage_and_foreign_versions():
    assert hist.decode_deltas("") == {}
    assert hist.decode_deltas("h9|x;s=1;n=1;b=0:1") == {}  # version
    assert hist.decode_deltas("h1|torn;s=1") == {}
    assert hist.decode_deltas("h1|x;s=1;n=1;b=99:1") == {}  # bad index
    # empty deltas encode to "" (nothing to send)
    assert hist.encode_deltas(
        {"x": {"sum": 0.0, "count": 0, "buckets": {}}}) == ""


def test_recent_windows_rotate():
    h = hist.Histogram()
    assert h.recent(1.0, now=0.0) is None
    h.observe(0.01)
    first = h.recent(1.0, now=0.0)     # establishes the mark
    assert first["count"] == 1
    for _ in range(5):
        h.observe(0.02)
    rotated = h.recent(1.0, now=2.0)   # window elapsed -> delta
    assert rotated["count"] == 5       # only the new observations
    # a read inside the next window returns the last COMPLETED delta
    h.observe(0.03)
    assert h.recent(1.0, now=2.5)["count"] == 5


# -- Timing integration ------------------------------------------------------

def test_timing_feeds_histograms_and_percentiles():
    t = Timing()
    t.observe("phase", 0.002, n=4)
    with t.timeit("phase"):
        pass
    snap = t.hist_snapshot("phase")
    assert snap["count"] == 5
    assert t.percentile("phase", 0.5) is not None
    assert "phase" in t.histograms()
    assert t.histograms(names=("other",)) == {}
    assert t.hist_snapshot("missing") is None
    assert t.percentile("missing", 0.99) is None


def test_hist_global_off_switch():
    t = Timing()
    hist.set_enabled(False)
    try:
        t.observe("x", 0.01)
    finally:
        hist.set_enabled(True)
    # mean path unaffected, histogram path off
    assert t.summary()["x"]["count"] == 1
    assert t.hist_snapshot("x") is None
    t.observe("x", 0.01)
    assert t.hist_snapshot("x")["count"] == 1


# -- slo.py ------------------------------------------------------------------

def test_rule_parse_and_reject():
    r = slo.SloRule("p99(batcher.queue_wait) < 0.05")
    assert (r.fn, r.source, r.op, r.threshold) == (
        "p99", "batcher.queue_wait", "<", 0.05)
    assert slo.SloRule("value(x) >= 1e-3", name="n").name == "n"
    assert slo.SloRule("mean(a.b) > 2").fn == "mean"
    with pytest.raises(ValueError):
        slo.SloRule("p99 batcher < 1")
    with pytest.raises(ValueError):
        slo.SloRule("max(x) < 1")


def test_breach_episodes_and_recorder_event():
    recorder = tracing.FlightRecorder(64)
    tracer = tracing.Tracer(recorder=recorder, enabled=True)
    wd = slo.SloWatchdog(tracer=tracer)
    box = {"v": 1.0}
    wd.add_source("freshness", lambda: box["v"])
    wd.add_rule("value(freshness) < 10", name="fresh")
    assert wd.evaluate()["fresh"]["ok"]
    box["v"] = 50.0
    r = wd.evaluate()
    assert not r["fresh"]["ok"] and r["fresh"]["breached_now"]
    wd.evaluate()  # still breaching: same EPISODE, no second event
    box["v"] = 2.0
    wd.evaluate()  # recover
    box["v"] = 99.0
    wd.evaluate()  # second episode
    payload = wd.payload(evaluate=False)
    assert payload["rules"]["fresh"]["breach_total"] == 2
    breaches = [e for e in recorder.snapshot()
                if e and e.get("name") == "slo.breach"]
    assert len(breaches) == 2
    assert breaches[0]["attrs"]["rule"] == "fresh"
    assert breaches[0]["attrs"]["threshold"] == 10.0


def test_no_data_and_broken_sources_never_breach():
    wd = slo.SloWatchdog()
    wd.add_source("gone", lambda: None)
    wd.add_rule("value(gone) < 1", name="gone")
    wd.add_source("boom", lambda: 1 / 0)
    wd.add_rule("value(boom) < 1", name="boom")
    wd.add_rule("p99(never_observed) < 1", name="unbound")
    results = wd.evaluate()
    assert all(r["ok"] for r in results.values())
    assert wd.payload(evaluate=False)["breaching"] == []


def test_pxx_rules_resolve_bound_timing():
    t = Timing()
    for _ in range(100):
        t.observe("lat", 0.2)
    wd = slo.SloWatchdog(tracer=tracing.Tracer(
        recorder=tracing.FlightRecorder(8), enabled=True))
    wd.bind_timing(t)
    wd.add_rule("p99(lat) < 0.05", name="lat")
    assert not wd.evaluate()["lat"]["ok"]


def test_arm_from_env_skips_bad_specs():
    wd = slo.SloWatchdog()
    wd.arm_from_env("myname=value(x) < 3; p95(y) > 0.1; garbage;;")
    assert wd.rule_count == 2
    payload = wd.payload(evaluate=True)
    assert set(payload["rules"]) == {"myname", "p95_y"}


def test_alertz_served_by_status_server(monkeypatch):
    wd = slo.SloWatchdog()
    wd.add_source("x", lambda: 5.0)
    wd.add_rule("value(x) < 1", name="x_low")
    monkeypatch.setattr(slo, "_WATCHDOG", wd)
    tm = TaskManager(training_shards=[("f", 0, 32)],
                     records_per_task=32)
    server = StatusServer(tm, port=0, host="127.0.0.1")
    server.start()
    try:
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/alertz" % server.port) as resp:
            body = json.loads(resp.read())
        assert body["breaching"] == ["x_low"]
        assert body["rules"]["x_low"]["value"] == 5.0
        # the status payload carries the slo section for /metrics
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % server.port) as resp:
            text = resp.read().decode()
        assert 'elasticdl_slo_ok{rule="x_low"} 0' in text
        assert 'elasticdl_slo_breach_total{rule="x_low"}' in text
    finally:
        server.stop()


# -- /profilez ---------------------------------------------------------------

class _FakeProfiler:
    def __init__(self, fail=False):
        self.calls = []
        self.fail = fail

    def start_trace(self, path):
        if self.fail:
            raise RuntimeError("no profiler backend")
        self.calls.append(("start", path))

    def stop_trace(self):
        self.calls.append(("stop", None))


def test_profilez_capture_links_trace(tmp_path):
    recorder = tracing.FlightRecorder(64)
    tracer = tracing.Tracer(recorder=recorder, enabled=True)
    fake = _FakeProfiler()
    with tracer.span("worker.task", task=7):
        body = json.loads(tracing.profilez_body(
            "/profilez?secs=0", trace_dir=str(tmp_path),
            profiler=fake, tracer=tracer))
    assert body["ok"]
    assert body["dir"].startswith(str(tmp_path))
    assert os.path.isdir(body["dir"])
    assert [c[0] for c in fake.calls] == ["start", "stop"]
    # the capture event is in the ring, inside the requesting trace
    capture = [e for e in recorder.snapshot()
               if e and e.get("name") == "profile.capture"]
    assert capture and capture[0]["attrs"]["dir"] == body["dir"]
    assert body["trace"] == capture[0]["trace"]


def test_profilez_bad_query_and_failing_backend(tmp_path):
    assert not json.loads(
        tracing.profilez_body("/profilez?secs=abc"))["ok"]
    tracer = tracing.Tracer(recorder=tracing.FlightRecorder(8),
                            enabled=True)
    body = json.loads(tracing.profilez_body(
        "/profilez?secs=0", trace_dir=str(tmp_path),
        profiler=_FakeProfiler(fail=True), tracer=tracer))
    assert not body["ok"] and "no profiler backend" in body["error"]
    # the in-progress guard released: a second capture may run
    body2 = json.loads(tracing.profilez_body(
        "/profilez?secs=0", trace_dir=str(tmp_path),
        profiler=_FakeProfiler(), tracer=tracer))
    assert body2["ok"]


# -- SIGQUIT live dump -------------------------------------------------------

@pytest.mark.skipif(not hasattr(signal, "SIGQUIT"),
                    reason="platform without SIGQUIT")
def test_sigquit_dumps_ring_without_exiting(tmp_path):
    """kill -QUIT a wedged process: the ring lands on disk and the
    process KEEPS RUNNING (live inspection), unlike SIGTERM."""
    script = (
        "import os, sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from elasticdl_tpu.utils import tracing\n"
        "tracing.configure_identity('quitproc')\n"
        "tracing.event('alive')\n"
        "tracing.arm_crash_dump()\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n" % REPO
    )
    env = dict(os.environ, ELASTICDL_TRACE_DIR=str(tmp_path),
               JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", script], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "ready"
        os.kill(proc.pid, signal.SIGQUIT)
        deadline = time.monotonic() + 10
        dump = None
        while time.monotonic() < deadline and dump is None:
            dumps = [f for f in os.listdir(str(tmp_path))
                     if f.endswith(".trace.json")]
            if dumps:
                dump = dumps[0]
            else:
                time.sleep(0.05)
        assert dump is not None, "no dump after SIGQUIT"
        # STILL ALIVE: that is the whole point
        time.sleep(0.2)
        assert proc.poll() is None
        with open(os.path.join(str(tmp_path), dump)) as f:
            events = json.load(f)["events"]
        names = [e.get("name") for e in events if e]
        assert "alive" in names and "sigquit" in names
    finally:
        proc.kill()
        proc.wait(timeout=10)


# -- master aggregation + straggler detection --------------------------------

def _hist_payload(values):
    h = hist.Histogram()
    for v in values:
        h.observe(v)
    return hist.encode_deltas(
        {"step_time": hist.delta(h.snapshot(), None)})


def _report(servicer, worker_id, values, steps=10):
    servicer.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=worker_id, record_count=32, steps_done=steps,
        steps_per_sec=5.0, hist_delta=_hist_payload(values)))


def _servicer():
    tm = TaskManager(training_shards=[("f", 0, 64)],
                     records_per_task=32)
    return MasterServicer(tm)


def test_hist_delta_ingest_feeds_job_p50_p99():
    sv = _servicer()
    _report(sv, 1, [0.01] * 8)
    _report(sv, 2, [0.02] * 8)
    tele = sv.telemetry()
    job = tele["job"]
    assert job["step_hist"]["count"] == 16
    assert job["step_time_p50_ms"] < job["step_time_p99_ms"]
    # /metrics renders the job histogram natively
    text = to_prometheus({
        "tasks": {"todo": 0, "doing": 0, "epoch": 0,
                  "completed": {}, "failed": {}},
        "finished": False, "telemetry": tele,
    })
    assert "elasticdl_job_step_time_seconds_bucket" in text
    assert "elasticdl_job_step_time_seconds_count 16" in text


def test_garbage_hist_delta_is_dropped_not_fatal():
    sv = _servicer()
    sv.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=1, record_count=32, steps_done=1,
        hist_delta="h1|torn-garbage"))
    assert "step_hist" not in sv.telemetry()["job"]


def test_straggler_flagged_within_sweeps_and_surfaced():
    """The acceptance shape: a deliberately slow worker is FLAGGED on
    the first sweep that sees its skewed window and SUSTAINED within
    STRAGGLER_SUSTAIN_SWEEPS — surfaced on telemetry/'/status',
    /metrics (elasticdl_worker_straggler), and as an slo.breach in
    the flight recorder + /alertz via the straggler source."""
    sv = _servicer()
    recorder = tracing.FlightRecorder(64)
    tracer = tracing.Tracer(recorder=recorder, enabled=True)
    wd = slo.SloWatchdog(tracer=tracer)
    wd.add_source("straggler_workers",
                  lambda: float(len(sv.stragglers())))
    wd.add_rule("value(straggler_workers) < 1", name="stragglers")

    for sweep in range(sv.STRAGGLER_SUSTAIN_SWEEPS):
        _report(sv, 1, [0.01] * 8)   # healthy
        _report(sv, 2, [0.01] * 8)   # healthy
        _report(sv, 3, [0.2] * 8)    # 20x the median: the straggler
        sv.straggler_sweep()
        wd.evaluate()
        if sweep == 0:
            # flagged within ONE sweep cadence of reporting skew
            with sv._lock:
                assert sv._straggler_state[3]["flagged"] == 1
            assert sv.stragglers() == []  # not yet sustained
    assert sv.stragglers() == [3]
    tele = sv.telemetry()
    assert tele["workers"][3]["straggler"] is True
    assert tele["workers"][1]["straggler"] is False
    assert tele["workers"][3]["step_p50_ms"] > (
        tele["workers"][1]["step_p50_ms"])
    text = to_prometheus({
        "tasks": {"todo": 0, "doing": 0, "epoch": 0,
                  "completed": {}, "failed": {}},
        "finished": False, "telemetry": tele,
    })
    assert 'elasticdl_worker_straggler{worker="3"} 1' in text
    assert 'elasticdl_worker_straggler{worker="1"} 0' in text
    # straggler event in the recorder + SLO breach on /alertz
    names = [e.get("name") for e in recorder.snapshot() if e]
    assert "slo.breach" in names
    assert not wd.payload(evaluate=False)["rules"]["stragglers"]["ok"]
    straggle = [e for e in tracing.default_tracer().recorder.snapshot()
                if e and e.get("name") == "worker.straggler"]
    assert any(e["attrs"]["worker"] == 3 for e in straggle)


def test_straggler_detectable_in_two_worker_job_and_recovers():
    """Leave-one-out median: even a TWO-worker job can flag its slow
    member (a plain median caps the ratio at 2.0 there), and the flag
    clears on the first healthy window."""
    sv = _servicer()
    for _ in range(sv.STRAGGLER_SUSTAIN_SWEEPS):
        _report(sv, 1, [0.01] * 8)
        _report(sv, 2, [0.2] * 8)
        sv.straggler_sweep()
    assert sv.stragglers() == [2]
    _report(sv, 1, [0.01] * 8)
    _report(sv, 2, [0.01] * 8)  # recovered
    sv.straggler_sweep()
    assert sv.stragglers() == []


def test_straggler_needs_min_samples_and_two_workers():
    sv = _servicer()
    _report(sv, 1, [0.2] * 8)
    assert sv.straggler_sweep() == []  # one worker: skew undefined
    _report(sv, 1, [0.01] * 8)
    _report(sv, 2, [0.5] * 2)  # below STRAGGLER_MIN_SAMPLES
    sv.straggler_sweep()
    with sv._lock:
        assert sv._straggler_state.get(2, {}).get("flagged", 0) == 0


def test_rpc_handle_histograms_exposed():
    sv = _servicer()
    sv.get_task(pb.GetTaskRequest(worker_id=0))
    _report(sv, 1, [0.01] * 4)
    hists = sv.rpc_histograms()
    assert hists["get_task"]["count"] == 1
    assert hists["report_batch_done"]["count"] == 1


# -- ResizeController policy term --------------------------------------------

def test_resize_controller_prefers_straggler_donor():
    from tests.test_scheduler import make_cluster

    registry, ctrl, sv, _jobs = make_cluster(
        [dict(name="a", n_tasks=8), dict(name="b", n_tasks=2)],
        pool_size=4,
    )
    b_tasks = {}
    for wid in range(4):
        res = sv.get_task(pb.GetTaskRequest(worker_id=wid))
        if res.job_id == 2:
            b_tasks[wid] = res.task.id
    b_workers = sorted(b_tasks)
    assert len(b_workers) == 2
    # Drop job b's demand below its 2 workers (complete one task):
    # b becomes over-target and donates one worker.  Newest-first
    # would donate max(b_workers); flag the OLDER one as a sustained
    # straggler and the policy term must pick IT instead.
    straggler = min(b_workers)
    sv.report_task_result(pb.ReportTaskResultRequest(
        task_id=b_tasks[straggler], job_id=2))
    ctrl._stragglers = {straggler}
    moves = ctrl._rebalance()
    assert (straggler, 2, 1) in moves


def test_step_throttle_spec_targets_one_worker():
    from elasticdl_tpu.worker.worker import step_throttle_secs

    assert step_throttle_secs(1, "1:120") == pytest.approx(0.12)
    assert step_throttle_secs(0, "1:120") == 0.0
    assert step_throttle_secs(2, "1:120,2:50") == pytest.approx(0.05)
    assert step_throttle_secs(1, "") == 0.0
    assert step_throttle_secs(1, "garbage,1:oops") == 0.0  # loud skip


# -- elastic-lint EL010 ------------------------------------------------------

def _el010(source):
    from tools.elastic_lint import check_source

    return [f for f in check_source(source, "fixture.py")
            if f.rule == "EL010"]


def test_el010_flags_undeclared_series():
    bad = (
        "def render(lines):\n"
        "    lines.append(prometheus_line("
        "'elasticdl_slo_okk', 1))\n"   # typo'd
    )
    findings = _el010(bad)
    assert len(findings) == 1
    assert "elasticdl_slo_okk" in findings[0].message


def test_el010_accepts_declared_series_and_templates():
    good = (
        "def render(lines, kind, snap):\n"
        "    lines.append(prometheus_line("
        "'elasticdl_workers_live', 3))\n"
        "    lines.append(prometheus_line("
        "'elasticdl_tasks_%s' % kind, 1))\n"
        "    histogram_lines(lines, "
        "'elasticdl_job_step_time_seconds', snap)\n"
        "    lines.append(prometheus_line(other_metric, 1))\n"  # dynamic:
        # out of scope by design (exposition test catches at render)
    )
    assert _el010(good) == []


def test_el010_flags_histogram_gauge_kind_mismatch():
    bad = (
        "def render(lines, snap):\n"
        "    histogram_lines(lines, "
        "'elasticdl_workers_live', snap)\n"      # declared gauge
        "    lines.append(prometheus_line("
        "'elasticdl_job_step_time_seconds', 1))\n"  # declared histogram
    )
    findings = _el010(bad)
    assert len(findings) == 2
    assert all("declared" in f.message for f in findings)
