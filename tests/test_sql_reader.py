import numpy as np

from elasticdl_tpu.data.sql_reader import SQLTableDataReader, SQLTableWriter
from elasticdl_tpu.master.task_manager import TaskManager


def test_sql_reader_shards_and_reads(tmp_path):
    db = str(tmp_path / "data.db")
    writer = SQLTableWriter(db, "samples", ["f0", "f1", "label"])
    rows = [[float(i), float(i * 2), i % 2] for i in range(95)]
    writer.write(rows)
    writer.close()

    reader = SQLTableDataReader(db, "samples", records_per_shard=30)
    assert reader.get_size() == 95
    assert reader.columns == ["f0", "f1", "label"]
    shards = reader.create_shards()
    assert [s[2] - s[1] for s in shards] == [30, 30, 30, 5]

    tm = TaskManager(training_shards=shards, records_per_task=30)
    seen = []
    while True:
        task = tm.get(0)
        if task is None:
            break
        for record in reader.read_records(task):
            seen.append(record[0])
        tm.report(task.id, True)
    np.testing.assert_array_equal(sorted(seen), [float(i) for i in
                                                 range(95)])
