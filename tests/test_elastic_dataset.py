"""RecordIndexService / ElasticDataset with a live master, plus a real
torch DataLoader driving the elastic index stream."""

import numpy as np
import pytest

from elasticdl_tpu.api.dataset import ElasticDataset
from tests.test_utils import create_master, create_master_client


def test_record_index_service_covers_all_records():
    master = create_master(
        training_shards=[("f", 0, 40)], records_per_task=16
    )
    try:
        mc = create_master_client(master)
        source = list(range(1000, 1040))
        dataset = ElasticDataset(source, mc, batch_size=8)
        seen = []
        while True:
            try:
                seen.append(dataset[0])
            except IndexError:
                break
            dataset.report_batch_done(1)
        assert sorted(v - 1000 for v in seen) == list(range(40))
        assert master.task_manager.finished()
    finally:
        dataset.stop()
        master.stop()


def test_elastic_dataset_with_torch_dataloader():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, Dataset

    master = create_master(
        training_shards=[("f", 0, 64)], records_per_task=16
    )
    try:
        mc = create_master_client(master)
        xs = np.arange(64, dtype=np.float32)

        class Source:
            def __getitem__(self, i):
                return xs[i]

        elastic = ElasticDataset(Source(), mc, batch_size=8)

        class TorchView(Dataset):
            def __len__(self):
                return 64  # upper bound for the sampler

            def __getitem__(self, i):
                value = elastic[i]
                return torch.tensor(value)

        loader = DataLoader(TorchView(), batch_size=8, num_workers=0)
        total = []
        try:
            for batch in loader:
                total.extend(batch.tolist())
                elastic.report_batch_done(len(batch))
        except IndexError:
            pass
        assert sorted(int(v) for v in total) == list(range(64))
    finally:
        elastic.stop()
        master.stop()
