"""PS servicer semantics over real in-process gRPC (reference pattern:
pserver_servicer_test.py:107-533, go server_test.go:85-265)."""

import os

import numpy as np
import pytest

from elasticdl_tpu.proto import rpc
from elasticdl_tpu.ps.optimizer import create_optimizer
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.worker.ps_client import PSClient


def start_ps(num_ps=1, opt_type="sgd", opt_args="learning_rate=0.1",
             **kwargs):
    """Boot N in-process PS shards; returns (PSClient, [servicers],
    [servers])."""
    servers, servicers, channels = [], [], []
    for i in range(num_ps):
        params = Parameters()
        servicer = PserverServicer(
            params,
            create_optimizer(opt_type, opt_args),
            ps_id=i, num_ps=num_ps, **kwargs,
        )
        server = grpc_utils.build_server(max_workers=8)
        rpc.add_pserver_servicer(servicer, server)
        port = server.add_insecure_port("[::]:0")
        server.start()
        channel = grpc_utils.build_channel("localhost:%d" % port)
        grpc_utils.wait_for_channel_ready(channel)
        servers.append(server)
        servicers.append(servicer)
        channels.append(channel)
    return PSClient(channels), servicers, servers


def stop_all(servers):
    for s in servers:
        s.stop(grace=None)


def test_push_to_init_and_pull():
    client, servicers, servers = start_ps(num_ps=2)
    try:
        initialized, _, _ = client.pull_dense_parameters(-1)
        assert not initialized
        dense = {"layer%d/w" % i: np.random.rand(3).astype(np.float32)
                 for i in range(6)}
        client.push_model(dense)
        initialized, version, pulled = client.pull_dense_parameters(-1)
        assert initialized and version == 0
        assert set(pulled) == set(dense)
        for k in dense:
            np.testing.assert_array_equal(pulled[k], dense[k])
    finally:
        stop_all(servers)


def test_async_push_gradients_applies_immediately():
    client, servicers, servers = start_ps(num_ps=1, use_async=True)
    try:
        w = np.ones(4, np.float32)
        client.push_model({"w": w})
        accepted, version = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=0
        )
        assert accepted and version == 1
        _, _, pulled = client.pull_dense_parameters(-1)
        np.testing.assert_allclose(pulled["w"], 1 - 0.1 * 0.5)
        # second push bumps version again
        accepted, version = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=1
        )
        assert version == 2
    finally:
        stop_all(servers)


def test_async_staleness_modulated_lr():
    # Async SGD with lr_staleness_modulation: a gradient computed
    # against version v applied at version V steps with lr/(V-v)
    # (reference go/pkg/ps/server.go staleness lr, python
    # servicer.py:124-167 semantics).
    client, servicers, servers = start_ps(
        num_ps=1, use_async=True, lr_staleness_modulation=True,
    )
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        # Two fresh pushes raise the version to 2.
        client.push_gradients({"w": np.zeros(4, np.float32)}, version=0)
        client.push_gradients({"w": np.zeros(4, np.float32)}, version=1)
        # Now a stale push: grad_version=0 vs version=2 -> staleness 2,
        # effective lr = 0.1 / 2.
        client.push_gradients(
            {"w": np.full(4, 1.0, np.float32)}, version=0
        )
        _, _, pulled = client.pull_dense_parameters(-1)
        np.testing.assert_allclose(
            pulled["w"], 1 - 0.1 / 2, rtol=1e-6
        )
    finally:
        stop_all(servers)


def test_sync_waits_and_averages():
    client, servicers, servers = start_ps(
        num_ps=1, use_async=False, grads_to_wait=2
    )
    try:
        client.push_model({"w": np.zeros(2, np.float32)})
        a1, v1 = client.push_gradients(
            {"w": np.array([1.0, 1.0], np.float32)}, version=0
        )
        assert a1 and v1 == 0  # buffered, not applied
        a2, v2 = client.push_gradients(
            {"w": np.array([3.0, 3.0], np.float32)}, version=0
        )
        assert a2 and v2 == 1  # applied: mean grad = 2.0
        _, _, pulled = client.pull_dense_parameters(-1)
        np.testing.assert_allclose(pulled["w"], -0.1 * 2.0)
    finally:
        stop_all(servers)


def test_sync_rejects_stale_gradients():
    client, servicers, servers = start_ps(
        num_ps=1, use_async=False, grads_to_wait=1,
        sync_version_tolerance=0,
    )
    try:
        client.push_model({"w": np.zeros(2, np.float32)})
        client.push_gradients({"w": np.ones(2, np.float32)}, version=0)
        # server is now at version 1; version-0 grads are stale
        accepted, version = client.push_gradients(
            {"w": np.ones(2, np.float32)}, version=0
        )
        assert not accepted and version == 1
    finally:
        stop_all(servers)


def test_embedding_pull_and_sparse_update():
    client, servicers, servers = start_ps(num_ps=2)
    try:
        infos = [{"name": "emb", "dim": 4, "initializer": "zeros"}]
        client.push_model({"w": np.zeros(1, np.float32)},
                          embedding_infos=infos)
        ids = np.array([0, 1, 5, 9, 12], np.int64)
        rows = client.pull_embedding_vectors("emb", ids)
        assert rows.shape == (5, 4)
        np.testing.assert_array_equal(rows, 0)
        # push sparse grads (with a duplicate id that must merge)
        grads = np.ones((3, 4), np.float32)
        client.push_gradients(
            {}, {"emb": (grads, np.array([1, 5, 1], np.int64))},
            version=0,
        )
        rows = client.pull_embedding_vectors("emb", np.array([1, 5]))
        np.testing.assert_allclose(rows[0], -0.1 * 2.0)  # merged dup
        np.testing.assert_allclose(rows[1], -0.1 * 1.0)
    finally:
        stop_all(servers)


def test_graceful_preemption_checkpoint_now(tmp_path):
    """checkpoint_now (the SIGTERM path, ps/server.py stop(
    checkpoint=True)) persists the CURRENT version even with periodic
    checkpointing disabled — the only save a preempted shard gets."""
    saver_dir = str(tmp_path)
    client, servicers, servers = start_ps(
        num_ps=1, use_async=True,
        checkpoint_saver=CheckpointSaver(saver_dir), checkpoint_steps=0,
    )
    try:
        client.push_model({"w": np.ones(3, np.float32)})
        client.push_gradients({"w": np.ones(3, np.float32)}, {},
                              version=0)
        assert not any(
            name.startswith("version-")
            for name in os.listdir(saver_dir)
        )  # periodic saves off
        servicers[0].checkpoint_now()
    finally:
        stop_all(servers)
    dense, _, version = CheckpointSaver(saver_dir).load_shard(None, 0, 1)
    assert version == 1
    np.testing.assert_allclose(dense["w"], 1 - 0.1)


def test_checkpoint_and_restore_roundtrip(tmp_path):
    saver_dir = str(tmp_path)
    client, servicers, servers = start_ps(
        num_ps=1, use_async=True,
        checkpoint_saver=CheckpointSaver(saver_dir), checkpoint_steps=1,
    )
    try:
        infos = [{"name": "emb", "dim": 2, "initializer": "zeros"}]
        client.push_model({"w": np.ones(3, np.float32)},
                          embedding_infos=infos)
        client.push_gradients(
            {"w": np.ones(3, np.float32)},
            {"emb": (np.ones((1, 2), np.float32),
                     np.array([7], np.int64))},
            version=0,
        )
    finally:
        stop_all(servers)
    # restore into a fresh PS via checkpoint_dir_for_init path
    saver = CheckpointSaver(saver_dir)
    dense, embeddings, version = saver.load_shard(None, 0, 1)
    assert version == 1
    np.testing.assert_allclose(dense["w"], 1 - 0.1)
    ids, values = embeddings["emb"]
    assert 7 in ids.tolist()
