import numpy as np
import pytest

from elasticdl_tpu.utils import tensor_codec


def test_ndarray_roundtrip():
    for dtype in ("float32", "float64", "int32", "int64", "uint8"):
        a = (np.arange(24).reshape(2, 3, 4) % 7).astype(dtype)
        b = tensor_codec.pb_to_ndarray(tensor_codec.ndarray_to_pb(a))
        np.testing.assert_array_equal(a, b)
        assert b.dtype == a.dtype


def test_bfloat16_roundtrip():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    a = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
    b = tensor_codec.pb_to_ndarray(tensor_codec.ndarray_to_pb(a))
    assert b.dtype == a.dtype
    np.testing.assert_array_equal(
        a.astype(np.float32), b.astype(np.float32)
    )


def test_indexed_slices_roundtrip():
    values = np.random.rand(3, 4).astype(np.float32)
    ids = [7, 2, 7]
    s = tensor_codec.indexed_slices_to_pb(values, ids)
    v2, i2 = tensor_codec.pb_to_indexed_slices(s)
    np.testing.assert_array_equal(values, v2)
    np.testing.assert_array_equal(np.array(ids), i2)


def test_merge_indexed_slices_sums_duplicates():
    values = np.array([[1.0, 1.0], [2.0, 2.0], [4.0, 4.0]], np.float32)
    merged, uniq = tensor_codec.merge_indexed_slices(values, [5, 3, 5])
    np.testing.assert_array_equal(uniq, [3, 5])
    np.testing.assert_allclose(merged, [[2.0, 2.0], [5.0, 5.0]])


def test_model_pb_roundtrip():
    dense = {"w": np.ones((2, 2), np.float32)}
    emb = {"table": (np.random.rand(2, 3).astype(np.float32), [1, 9])}
    infos = [{"name": "table", "dim": 3}]
    m = tensor_codec.model_to_pb(
        dense=dense, embeddings=emb, infos=infos, version=7
    )
    d2, e2, i2, v = tensor_codec.pb_to_model(m)
    assert v == 7
    np.testing.assert_array_equal(d2["w"], dense["w"])
    np.testing.assert_array_equal(e2["table"][1], [1, 9])
    assert i2[0]["name"] == "table" and i2[0]["dim"] == 3


def test_wire_dtype_bf16_roundtrip_upcasts_to_f32():
    pytest.importorskip("ml_dtypes")
    a = np.random.default_rng(0).standard_normal((5, 7)).astype(
        np.float32
    )
    t = tensor_codec.ndarray_to_pb(a, wire_dtype="bfloat16")
    assert t.dtype == "float32" and t.wire_dtype == "bfloat16"
    assert len(t.content) == a.size * 2  # half the f32 bytes
    b = tensor_codec.pb_to_ndarray(t)
    assert b.dtype == np.float32
    np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-2)


def test_wire_dtype_ignored_for_non_f32_and_cleared_on_reuse():
    pytest.importorskip("ml_dtypes")
    ints = np.arange(4, dtype=np.int64)
    t = tensor_codec.ndarray_to_pb(ints, wire_dtype="bfloat16")
    assert t.wire_dtype == ""  # ids/ints never downcast
    np.testing.assert_array_equal(tensor_codec.pb_to_ndarray(t), ints)
    # reusing a message that previously carried bf16 must clear the
    # wire marker, or the f32 payload would be misdecoded
    reuse = tensor_codec.ndarray_to_pb(
        np.ones(3, np.float32), wire_dtype="bfloat16"
    )
    tensor_codec.ndarray_to_pb(np.ones(3, np.float32), out=reuse)
    assert reuse.wire_dtype == ""
    assert tensor_codec.pb_to_ndarray(reuse).dtype == np.float32


def test_model_pb_wire_dtype_compresses_floats_not_ids():
    pytest.importorskip("ml_dtypes")
    dense = {"w": np.random.rand(8, 4).astype(np.float32)}
    emb = {"t": (np.random.rand(3, 4).astype(np.float32),
                 np.array([5, 1, 9], np.int64))}
    m = tensor_codec.model_to_pb(
        dense=dense, embeddings=emb, wire_dtype="bfloat16"
    )
    assert m.dense_parameters["w"].wire_dtype == "bfloat16"
    assert m.embedding_tables["t"].values.wire_dtype == "bfloat16"
    d2, e2, _, _ = tensor_codec.pb_to_model(m)
    assert d2["w"].dtype == np.float32
    values, ids = e2["t"]
    assert values.dtype == np.float32
    np.testing.assert_array_equal(ids, [5, 1, 9])  # ids exact
    np.testing.assert_allclose(d2["w"], dense["w"], atol=1e-2)


def test_merge_indexed_slices_matches_add_at_reference():
    rng = np.random.default_rng(7)
    for n, vocab in [(0, 10), (1, 10), (300, 40), (2000, 5000)]:
        ids = rng.integers(0, vocab, size=n).astype(np.int64)
        values = rng.standard_normal((n, 6)).astype(np.float32)
        uniq, inverse = np.unique(ids, return_inverse=True)
        ref = np.zeros((uniq.size, 6), np.float32)
        np.add.at(ref, inverse, values)
        merged, out_ids = tensor_codec.merge_indexed_slices(values, ids)
        np.testing.assert_array_equal(out_ids, uniq)
        np.testing.assert_allclose(merged, ref, rtol=1e-5, atol=1e-6)
        assert merged.dtype == np.float32


def test_merge_indexed_slices_unique_fast_paths():
    values = np.arange(6, dtype=np.float32).reshape(3, 2)
    # pre-sorted unique ids: pass-through, no copy
    merged, uniq = tensor_codec.merge_indexed_slices(values, [2, 5, 9])
    assert merged is values
    np.testing.assert_array_equal(uniq, [2, 5, 9])
    # unsorted unique ids: rows gathered into sorted-id order
    merged, uniq = tensor_codec.merge_indexed_slices(values, [9, 2, 5])
    np.testing.assert_array_equal(uniq, [2, 5, 9])
    np.testing.assert_allclose(merged, values[[1, 2, 0]])


def test_timing_counters():
    from elasticdl_tpu.utils.timing import Timing

    timing = Timing()
    timing.bump("prefetch_hit")
    timing.bump("prefetch_hit", 2)
    timing.bump("push_window_stall")
    assert timing.counters() == {
        "prefetch_hit": 3, "push_window_stall": 1,
    }
    timing.reset()
    assert timing.counters() == {}
    disabled = Timing(enabled=False)
    disabled.bump("x")
    assert disabled.counters() == {}
