import numpy as np
import pytest

from elasticdl_tpu.utils import tensor_codec


def test_ndarray_roundtrip():
    for dtype in ("float32", "float64", "int32", "int64", "uint8"):
        a = (np.arange(24).reshape(2, 3, 4) % 7).astype(dtype)
        b = tensor_codec.pb_to_ndarray(tensor_codec.ndarray_to_pb(a))
        np.testing.assert_array_equal(a, b)
        assert b.dtype == a.dtype


def test_bfloat16_roundtrip():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    a = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
    b = tensor_codec.pb_to_ndarray(tensor_codec.ndarray_to_pb(a))
    assert b.dtype == a.dtype
    np.testing.assert_array_equal(
        a.astype(np.float32), b.astype(np.float32)
    )


def test_indexed_slices_roundtrip():
    values = np.random.rand(3, 4).astype(np.float32)
    ids = [7, 2, 7]
    s = tensor_codec.indexed_slices_to_pb(values, ids)
    v2, i2 = tensor_codec.pb_to_indexed_slices(s)
    np.testing.assert_array_equal(values, v2)
    np.testing.assert_array_equal(np.array(ids), i2)


def test_merge_indexed_slices_sums_duplicates():
    values = np.array([[1.0, 1.0], [2.0, 2.0], [4.0, 4.0]], np.float32)
    merged, uniq = tensor_codec.merge_indexed_slices(values, [5, 3, 5])
    np.testing.assert_array_equal(uniq, [3, 5])
    np.testing.assert_allclose(merged, [[2.0, 2.0], [5.0, 5.0]])


def test_model_pb_roundtrip():
    dense = {"w": np.ones((2, 2), np.float32)}
    emb = {"table": (np.random.rand(2, 3).astype(np.float32), [1, 9])}
    infos = [{"name": "table", "dim": 3}]
    m = tensor_codec.model_to_pb(
        dense=dense, embeddings=emb, infos=infos, version=7
    )
    d2, e2, i2, v = tensor_codec.pb_to_model(m)
    assert v == 7
    np.testing.assert_array_equal(d2["w"], dense["w"])
    np.testing.assert_array_equal(e2["table"][1], [1, 9])
    assert i2[0]["name"] == "table" and i2[0]["dim"] == 3
