"""Sliding-window attention across the whole stack (VERDICT r2 #3).

The round-2 regression shipped because no test passed window != 0
anywhere.  This file covers the band in every implementation: the Pallas
flash kernel (fwd + both backwards), the partial kernel ring attention
folds, the ring dispatch (skip / full / banded blocks), Ulysses, and the
transformer config plumbing — all against the dense reference
``_attention_ref(window=...)``.

Window values are chosen to hit the tile-arithmetic edges at t=384
(tile 128 -> a 3x3 block grid): W < tile, W not a multiple of 128, and
W >= t (must equal full causal).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.flash_attention import (
    _attention_ref,
    flash_attention,
    flash_attention_partial,
)
from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.parallel.ring_attention import (
    attention_local,
    ring_attention,
)
from elasticdl_tpu.parallel.ulysses import ulysses_attention

WINDOWS = [64, 200, 1000]  # < tile; not a multiple of 128; >= t


def make_bhtd(b=1, h=2, t=384, d=64, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, h, t, d)
    return tuple(
        jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)
    )


def make_bthd(b=2, t=64, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, t, h, d)
    return tuple(
        jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)
    )


@pytest.mark.parametrize("window", WINDOWS)
def test_flash_window_forward(window):
    q, k, v = make_bhtd()
    ref = _attention_ref(q, k, v, True, q.shape[-1] ** -0.5,
                         window=window)
    out = flash_attention(q, k, v, causal=True, interpret=True,
                          window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    if window >= q.shape[2]:
        full = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                                   rtol=2e-5, atol=2e-5)


def test_flash_window_requires_causal():
    q, k, v = make_bhtd(t=128)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, causal=False, window=64)
    with pytest.raises(ValueError):
        flash_attention_partial(q, k, v, causal=False, window=64)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, None, causal=False, window=64)
    qs, ks, vs = make_bthd(t=16)
    with pytest.raises(ValueError):
        attention_local(qs, ks, vs, causal=False, window=8)
    with pytest.raises(ValueError):
        ulysses_attention(qs, ks, vs, None, causal=False, window=8)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, causal=True, window=-1)


def test_banded_partial_matches_dense():
    """_partial_banded (the ring's straddling-block path, blockwise with
    checkpoint) == the dense banded reference, values and grads."""
    from elasticdl_tpu.ops.flash_attention import (
        _partial_banded,
        _partial_ref,
    )

    q, k, v = make_bhtd(b=1, h=1, t=256, d=32, seed=9)
    scale = q.shape[-1] ** -0.5
    for k_offset, window in ((-256, 300), (-128, 200), (0, 64)):
        dense = _partial_ref(q, k, v, True, scale, k_offset,
                             window=window)
        blockwise = _partial_banded(q, k, v, scale, k_offset, window)
        for a, b in zip(dense, blockwise):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)

    def norm_out(fn):
        def f(q, k, v):
            acc, l, m = fn(q, k, v)
            return (acc / jnp.maximum(l, 1e-30)[..., None]).sum()
        return f

    gd = jax.grad(norm_out(
        lambda q, k, v: _partial_ref(q, k, v, True, scale, -128,
                                     window=200)), argnums=(0, 1, 2),
    )(q, k, v)
    gb = jax.grad(norm_out(
        lambda q, k, v: _partial_banded(q, k, v, scale, -128, 200)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gd, gb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_window_blockwise_banded():
    """Shards long enough (T/sp=256, two 128-blocks) that the straddling
    ring step takes the blockwise _partial_banded path, not the dense
    fallback."""
    q, k, v = make_bthd(b=1, t=1024, h=1, d=32, seed=11)
    mesh = build_mesh(sp=4, devices=jax.devices()[:4])
    for window in (300, 700):
        ref = attention_local(q, k, v, causal=True, window=window,
                              mode="off")
        out = ring_attention(q, k, v, mesh, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", WINDOWS)
def test_flash_window_pallas_bwd(window, monkeypatch):
    """Both Pallas backward kernels under a window — the q_index /
    kv_index clamping and the in-kernel band mask."""
    import elasticdl_tpu.ops.flash_attention as fa

    called = {}
    orig = fa._pallas_bwd

    def spy(*args, **kwargs):
        called["yes"] = True
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "_pallas_bwd", spy)
    q, k, v = make_bhtd(seed=window)

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, causal=True, interpret=True,
                            window=window) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            _attention_ref(q, k, v, True, q.shape[-1] ** -0.5,
                           window=window) ** 2
        ).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert called.get("yes"), "pallas bwd was not invoked"
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("window", [64, 200])
def test_flash_window_xla_bwd(window, monkeypatch):
    """The block-recompute escape hatch must honor the window too."""
    import elasticdl_tpu.ops.flash_attention as fa

    monkeypatch.setenv("ELASTICDL_FLASH_BWD", "xla")
    q, k, v = make_bhtd(seed=3)

    def loss_flash(q, k, v):
        return (
            fa.flash_attention(q, k, v, causal=True, interpret=True,
                               window=window) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            fa._attention_ref(q, k, v, True, q.shape[-1] ** -0.5,
                              window=window) ** 2
        ).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("window", [64, 200])
def test_partial_window_matches_reference(window):
    """Windowed partial (acc, l, m) normalizes to the windowed dense
    output — the diagonal block of a windowed ring."""
    q, k, v = make_bhtd(t=256, seed=5)
    acc, l, m = flash_attention_partial(
        q, k, v, causal=True, interpret=True, window=window
    )
    out = np.asarray(acc) / np.maximum(np.asarray(l), 1e-30)[..., None]
    ref = _attention_ref(q, k, v, True, q.shape[-1] ** -0.5,
                         window=window)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("window", [64, 200])
def test_partial_window_grads(window):
    """The stats-based partial backward recomputes windowed scores."""
    from elasticdl_tpu.ops.flash_attention import _partial_ref

    q, k, v = make_bhtd(t=256, seed=7)
    scale = q.shape[-1] ** -0.5
    rng = np.random.RandomState(1)
    cot = (
        jnp.asarray(rng.randn(*q.shape).astype(np.float32)),
        jnp.asarray(rng.randn(*q.shape[:3]).astype(np.float32)),
        jnp.asarray(rng.randn(*q.shape[:3]).astype(np.float32)),
    )
    _, vjp_d = jax.vjp(
        lambda q, k, v: _partial_ref(q, k, v, True, scale, 0,
                                     window=window),
        q, k, v,
    )
    _, vjp_f = jax.vjp(
        lambda q, k, v: flash_attention_partial(
            q, k, v, causal=True, interpret=True, window=window
        ),
        q, k, v,
    )
    for a, b in zip(vjp_d(cot), vjp_f(cot)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=2e-3)


# -- ring / ulysses (layout [B, T, H, D], 8 virtual CPU devices) ------------


@pytest.mark.parametrize("sp", [2, 4])
# shard C = 64/sp: windows hit (inside-shard, straddling, multi-shard,
# >= T) so the skip / banded / full dispatch arms all run.
@pytest.mark.parametrize("window", [8, 20, 40, 100])
def test_ring_window_matches_local(sp, window):
    q, k, v = make_bthd()
    mesh = build_mesh(dp=2, tp=1, sp=sp, devices=jax.devices()[: 2 * sp])
    ref = attention_local(q, k, v, causal=True, window=window)
    out = ring_attention(q, k, v, mesh, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_ring_window_grad():
    q, k, v = make_bthd(b=1, t=32, h=2, d=16, seed=2)
    mesh = build_mesh(dp=1, tp=1, sp=4, devices=jax.devices()[:4])

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True, window=12).sum()

    def loss_ref(q, k, v):
        return attention_local(q, k, v, causal=True, window=12).sum()

    g = jax.jit(jax.grad(loss))(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_ring_window_grad_banded_scan():
    """Gradients THROUGH _partial_banded's checkpoint+scan branch.

    The sp=4/T=32 grad test above has shard T_k=8, so banded falls back
    to _partial_ref and the scan branch's backward was never covered
    (ADVICE r3).  Here shard T_k = 512/2 = 256 = 2 x 128-blocks, and
    window=300 makes the delta=1 ring step a straddling block: the
    multi-block scan + jax.checkpoint backward is on the grad path.
    """
    q, k, v = make_bthd(b=1, t=512, h=1, d=32, seed=9)
    mesh = build_mesh(dp=1, tp=1, sp=2, devices=jax.devices()[:2])

    def loss(q, k, v):
        out = ring_attention(q, k, v, mesh, causal=True, window=300)
        return (out * out).sum()

    def loss_ref(q, k, v):
        out = attention_local(q, k, v, causal=True, window=300,
                              mode="off")
        return (out * out).sum()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_window_flash_fold(monkeypatch):
    """Windowed ring with the Pallas partial kernel on the diagonal
    (interpret mode) — the windowed-kernel + banded-jnp mix."""
    monkeypatch.setenv("ELASTICDL_FLASH", "interpret")
    q, k, v = make_bthd(b=1, t=512, h=1, d=64, seed=4)
    mesh = build_mesh(sp=4, devices=jax.devices()[:4])
    for window in (100, 300):
        ref = attention_local(q, k, v, causal=True, window=window,
                              mode="off")
        out = ring_attention(q, k, v, mesh, causal=True, window=window)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("sp", [2, 4])
@pytest.mark.parametrize("window", [8, 20, 100])
def test_ulysses_window_matches_local(sp, window):
    q, k, v = make_bthd(seed=6)
    mesh = build_mesh(dp=2, tp=1, sp=sp, devices=jax.devices()[: 2 * sp])
    ref = attention_local(q, k, v, causal=True, window=window)
    out = ulysses_attention(q, k, v, mesh, causal=True, window=window)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_sp1_fallback_honors_window():
    """ADVICE r2 medium: the no-sp fallback used to silently drop the
    window."""
    q, k, v = make_bthd(seed=8)
    mesh = build_mesh(dp=2, tp=1, sp=1, devices=jax.devices()[:2])
    ref = attention_local(q, k, v, causal=True, window=16)
    out = ulysses_attention(q, k, v, mesh, causal=True, window=16)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_transformer_window_config():
    """cfg.window reaches the attention stack: a windowed forward
    differs from full causal and matches between ring and ulysses."""
    from elasticdl_tpu.models import transformer as tfm

    base = dict(vocab_size=64, dim=64, num_heads=4, num_layers=2,
                max_seq_len=64, dtype="float32")
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, size=(2, 64)), jnp.int32
    )
    mesh = build_mesh(dp=1, tp=1, sp=2, devices=jax.devices()[:2])
    cfg_full = tfm.TransformerConfig(**base)
    cfg_ring = tfm.TransformerConfig(window=16, **base)
    cfg_uly = tfm.TransformerConfig(window=16, attention_impl="ulysses",
                                    **base)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg_full)
    full = tfm.forward(params, tokens, cfg_full, mesh=mesh)
    ring = tfm.forward(params, tokens, cfg_ring, mesh=mesh)
    uly = tfm.forward(params, tokens, cfg_uly, mesh=mesh)
    assert not np.allclose(np.asarray(full), np.asarray(ring),
                           atol=1e-3), "window had no effect"
    np.testing.assert_allclose(np.asarray(ring), np.asarray(uly),
                               rtol=2e-4, atol=2e-4)
