import numpy as np

from elasticdl_tpu.models import heart
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer


def test_heart_learns(tmp_path):
    path = heart.synthetic_heart_csv(str(tmp_path / "heart.csv"), n=256)
    with open(path) as f:
        records = [line.strip().split(",") for line in f]
    spec = heart.model_spec(learning_rate=0.02)
    trainer = CollectiveTrainer(spec, batch_size=64)
    for _ in range(10):
        for i in range(0, 256, 64):
            xs, ys = spec.feed(records[i:i + 64])
            trainer.train_minibatch(xs, ys)
    correct, total = 0, 0
    for i in range(0, 256, 64):
        xs, ys = spec.feed(records[i:i + 64])
        out, labels = trainer.evaluate_minibatch(xs, ys)
        correct += ((out > 0) == labels).sum()
        total += len(labels)
    assert correct / total > 0.8


def test_model_params_string_reaches_spec():
    spec = load_model_spec(
        "transformer",
        model_params="vocab_size=128;dim=32;num_heads=2;num_layers=1;"
                     "seq_len=16",
    )
    assert spec.config.vocab_size == 128
    assert spec.config.dim == 32
    assert spec.config.num_layers == 1
