"""The fleet's online-loop surface (serving/router.py +
serving/fleet.py): external rollouts, canary keyspace slicing with
cohort isolation / promote / rollback, the autoscaler policy, and the
canary + aggregation /metrics series."""

import http.client
import json
import os
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.serving.export import export_servable
from elasticdl_tpu.serving.fleet import (
    FleetAutoscaler,
    FleetState,
    canary_slice,
)
from elasticdl_tpu.serving.router import Router, build_router_server
from elasticdl_tpu.serving.server import ModelEndpoint, build_server
from elasticdl_tpu.utils.prom import fleet_to_prometheus

W = np.arange(8, dtype=np.float32).reshape(4, 2)


def _export_version(base, version, bias=0.0):
    export_servable(
        os.path.join(str(base), str(version)),
        lambda p, x: x @ p["w"] + bias, {"w": W},
        np.zeros((1, 4), np.float32), model_name="lin",
        version=version, platforms=("cpu",),
    )


class _Replica:
    def __init__(self, base, **kwargs):
        kwargs.setdefault("fleet_managed", True)
        self.endpoint = ModelEndpoint(str(base), **kwargs)
        self.server = build_server(self.endpoint, port=0)
        self.addr = "127.0.0.1:%d" % self.server.server_address[1]
        self._dead = False
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def kill(self):
        """Close the listening socket — the observable signature of a
        dead replica process."""
        if not self._dead:
            self._dead = True
            self.server.shutdown()
            self.server.server_close()

    def close(self):
        self.kill()
        self.endpoint.close()


def _wait(predicate, timeout=20, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def fleet(tmp_path):
    """3 in-process replicas behind an externally-driven router."""
    base = tmp_path / "exports"
    _export_version(base, 1)
    replicas = [_Replica(base) for _ in range(3)]
    router = Router([r.addr for r in replicas], export_dir=str(base),
                    probe_interval=0.05, poll_interval=0.1,
                    auto_rollout=False)
    server = build_router_server(router, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever,
                     daemon=True).start()
    router.start(coordinate=True)
    assert _wait(lambda: router.coordinator.committed_version == 1
                 and len(router.state.routable(1)) == 3), (
        router.fleet_status())
    yield {"router": router, "port": port, "base": base,
           "replicas": replicas}
    router.stop()
    server.shutdown()
    server.server_close()
    for replica in replicas:
        replica.close()


def _post(port, path, payload):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    try:
        conn.request("POST", path, body=json.dumps(payload))
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _predict(port, key):
    status, out = _post(port, "/v1/models/lin:predict",
                        {"instances": [[1, 1, 1, 1]],
                         "routing_key": key})
    return status, out.get("model_version")


def _keys(n=200):
    return ["user-%d" % i for i in range(n)]


# -- external rollout --------------------------------------------------


def test_external_rollout_and_auto_rollout_off(fleet):
    router, port, base = (fleet["router"], fleet["port"],
                          fleet["base"])
    _export_version(base, 2, bias=1.0)
    # auto_rollout=False: the scan loop must NOT pick it up itself.
    time.sleep(0.5)
    assert router.coordinator.committed_version == 1
    status, out = _post(port, "/fleet/rollout",
                        {"version": 2, "freshness_seconds": 1.5})
    assert status == 200 and out["committed"]
    assert out["committed_version"] == 2
    assert _wait(lambda: len(router.state.routable(2)) == 3)
    # Freshness telemetry landed on the fleet status + /metrics.
    assert fleet["router"].fleet_status()["aggregation"][
        "freshness_seconds"] == 1.5
    text = fleet_to_prometheus(router.fleet_status())
    assert "elasticdl_agg_freshness_seconds 1.5" in text
    assert "elasticdl_agg_published_version 2" in text


def test_rollout_refuses_regression_and_repeats_idempotently(fleet):
    port = fleet["port"]
    _, out = _post(port, "/fleet/rollout", {"version": 1})
    assert out["committed"]  # already there: idempotent success
    _, out = _post(port, "/fleet/rollout", {"version": 0})
    assert not out["committed"]
    assert "behind committed" in out["error"]


# -- canary ------------------------------------------------------------


def test_canary_cohort_isolation_then_barrier_clean_promote(fleet):
    router, port, base = (fleet["router"], fleet["port"],
                          fleet["base"])
    _export_version(base, 2, bias=1.0)
    status, out = _post(port, "/fleet/canary",
                        {"version": 2, "fraction": 0.3})
    assert status == 200 and out["started"], out
    assert len(out["replicas"]) == 1  # ceil(0.3 * 3)
    canary_keys = [k for k in _keys() if canary_slice(k) < 0.3]
    baseline_keys = [k for k in _keys() if canary_slice(k) >= 0.3]
    # The deterministic hash puts ~30% of keys on the canary slice.
    assert 0.2 < len(canary_keys) / len(_keys()) < 0.4
    for key in canary_keys[:8]:
        assert _predict(port, key) == (200, 2)
    for key in baseline_keys[:8]:
        assert _predict(port, key) == (200, 1)
    cohorts = router.cohort_stats()
    assert cohorts["canary"]["keyed_requests"] == 8
    assert cohorts["canary"]["model_version"] == 2
    assert cohorts["baseline"]["model_version"] == 1
    status, out = _post(port, "/fleet/canary/promote", {})
    assert out["promoted"] and out["committed_version"] == 2
    assert router.canary_view() is None
    assert _wait(lambda: len(router.state.routable(2)) == 3)
    # Post-promote: every key sees version 2 — and no key ever saw a
    # version regression (canary keys went 2 -> 2, baseline 1 -> 2).
    for key in canary_keys[:4] + baseline_keys[:4]:
        assert _predict(port, key) == (200, 2)


def test_canary_rollback_returns_replicas_to_committed(fleet):
    router, port, base = (fleet["router"], fleet["port"],
                          fleet["base"])
    _export_version(base, 2, bias=1.0)
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 0.34})
    assert out["started"]
    canary_addrs = set(out["replicas"])
    canary_key = next(k for k in _keys() if canary_slice(k) < 0.34)
    assert _predict(port, canary_key) == (200, 2)
    status, out = _post(port, "/fleet/canary/rollback", {})
    assert out["rolled_back"] and set(out["healed"]) == canary_addrs
    assert router.canary_view() is None
    assert router.coordinator.committed_version == 1
    # The rolled-back replicas serve the committed version again and
    # rejoin the one routable pool.
    assert _wait(lambda: len(router.state.routable(1)) == 3)
    assert _predict(port, canary_key) == (200, 1)


def test_canary_fallback_counts_as_baseline_evidence(fleet):
    """A dead canary pool must not mint canary evidence: fallback
    requests are served by baseline replicas at the committed version,
    so they count (and version-stamp) as baseline."""
    router, port, base = (fleet["router"], fleet["port"],
                          fleet["base"])
    _export_version(base, 2, bias=1.0)
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 0.3})
    assert out["started"]
    # Kill the WHOLE canary pool mid-soak.
    for canary_addr in out["replicas"]:
        next(r for r in fleet["replicas"]
             if r.addr == canary_addr).kill()
    assert _wait(lambda: not any(
        router.state.replica_row(a)["healthy"]
        for a in out["replicas"]))
    before = router.cohort_stats()
    canary_key = next(k for k in _keys() if canary_slice(k) < 0.3)
    status, version = _predict(port, canary_key)
    assert (status, version) == (200, 1)  # served by baseline
    after = router.cohort_stats()
    assert after["canary"]["requests"] == before["canary"]["requests"]
    assert (after["baseline"]["requests"]
            == before["baseline"]["requests"] + 1)
    _, counters = router.state.snapshot()
    assert counters.get("router.canary_fallback", 0) >= 1
    _post(port, "/fleet/canary/rollback", {})


def test_seed_committed_is_modal_not_max():
    """A router restarting mid-canary must not adopt the lone canary
    replica's unvetted version as the fleet's committed one."""
    from elasticdl_tpu.serving.fleet import FleetCoordinator

    state = FleetState(["a:1", "b:2", "c:3"], probe_interval=9999)
    now = time.monotonic()
    state.note_probe_ok("a:1", {"models": {"m": {"version": 10}}}, now)
    state.note_probe_ok("b:2", {"models": {"m": {"version": 10}}}, now)
    state.note_probe_ok("c:3", {"models": {"m": {"version": 11}}}, now)
    coordinator = FleetCoordinator(state, "")
    assert coordinator.seed_committed()
    assert coordinator.committed_version == 10  # majority, not max
    # A 1-vs-1 tie keeps the MAX: that split is also the
    # lagging-rejoiner shape, whose heal-up is the PR-9 guarantee.
    state2 = FleetState(["a:1", "b:2"], probe_interval=9999)
    state2.note_probe_ok("a:1", {"models": {"m": {"version": 10}}},
                         now)
    state2.note_probe_ok("b:2", {"models": {"m": {"version": 11}}},
                         now)
    coordinator2 = FleetCoordinator(state2, "")
    assert coordinator2.seed_committed()
    assert coordinator2.committed_version == 11


def test_canary_explicit_replica_list_is_validated(fleet):
    port, base = fleet["port"], fleet["base"]
    _export_version(base, 2, bias=1.0)
    addrs = [r.addr for r in fleet["replicas"]]
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 0.3,
                    "replicas": ["127.0.0.1:1"]})
    assert not out["started"] and "not routable" in out["error"]
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 0.3, "replicas": addrs})
    assert not out["started"] and "baseline" in out["error"]
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 0.3,
                    "replicas": addrs[:1]})
    assert out["started"] and out["replicas"] == addrs[:1]
    _post(port, "/fleet/canary/rollback", {})


def test_publish_only_mode_still_runs_retention(tmp_path):
    from elasticdl_tpu.aggregation import ModelAggregator
    from elasticdl_tpu.aggregation.main import run_loop
    from elasticdl_tpu.serving.export import ContinuousExporter
    from elasticdl_tpu.serving.loader import list_versions

    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = ContinuousExporter(str(src), model_name="lin",
                            platforms=("cpu",))
    W = np.full((4, 2), 1.0, np.float32)

    def export(version):
        ce.export(version, lambda p, x: x @ p["w"], {"w": W},
                  np.zeros((1, 4), np.float32))

    agg = ModelAggregator(str(src), str(pub), window=1,
                          mode="latest", export_keep=1)
    stop = threading.Event()
    runner = threading.Thread(
        target=run_loop, args=(agg, stop),
        kwargs={"router": None, "poll_interval": 0.05}, daemon=True)
    runner.start()
    # Staggered exports -> three separate publishes.
    for version in (1, 2, 3):
        export(version)
        assert _wait(lambda v=version: agg.stats()
                     ["last_published_version"] == v, 20)
    stop.set()
    runner.join(timeout=10)
    # keep=1 with the newest publish as the floor: 1 and 2 are GC'd.
    assert list_versions(str(pub)) == [3]


def test_canary_input_validation(fleet):
    router, port, base = (fleet["router"], fleet["port"],
                          fleet["base"])
    _export_version(base, 2, bias=1.0)
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 1.5})
    assert not out["started"] and "fraction" in out["error"]
    _, out = _post(port, "/fleet/canary",
                   {"version": 1, "fraction": 0.3})
    assert not out["started"] and "not ahead" in out["error"]
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 0.3})
    assert out["started"]
    # One canary at a time; rollouts are refused while it runs.
    _, out = _post(port, "/fleet/canary",
                   {"version": 2, "fraction": 0.3})
    assert not out["started"] and "already active" in out["error"]
    _, out = _post(port, "/fleet/rollout", {"version": 2})
    assert not out["committed"] and "canary active" in out["error"]
    _post(port, "/fleet/canary/rollback", {})


def test_canary_needs_a_baseline_replica(tmp_path):
    base = tmp_path / "exports"
    _export_version(base, 1)
    replica = _Replica(base)
    router = Router([replica.addr], export_dir=str(base),
                    probe_interval=0.05, poll_interval=0.1,
                    auto_rollout=False)
    router.start(coordinate=True)
    try:
        assert _wait(
            lambda: len(router.state.routable(1)) == 1)
        _export_version(base, 2, bias=1.0)
        out = router.start_canary(2, 0.5)
        assert not out["started"]  # a 1-replica fleet can't slice
    finally:
        router.stop()
        replica.close()


def test_canary_metrics_and_label_escaping():
    """fleet_to_prometheus renders the canary series through the ONE
    prometheus_line renderer — label escaping included."""
    status = {
        "committed_version": 3,
        "replicas": {}, "counters": {},
        "canary": {
            "active": True, "version": 4, "fraction": 0.25,
            "replicas": ["a:1"],
            "cohorts": {
                'weird"cohort\n': {"requests": 2, "keyed_requests": 1,
                                   "errors": 1,
                                   "latency_ms_sum": 10.0,
                                   "model_version": 4},
            },
        },
        "aggregation": {"freshness_seconds": 2.5, "version": 4},
    }
    text = fleet_to_prometheus(status)
    assert "elasticdl_fleet_canary_active 1" in text
    assert "elasticdl_fleet_canary_version 4" in text
    assert "elasticdl_fleet_canary_fraction 0.25" in text
    assert ('elasticdl_fleet_canary_requests'
            '{cohort="weird\\"cohort\\n"} 2') in text
    assert ('elasticdl_fleet_canary_latency_ms'
            '{cohort="weird\\"cohort\\n"} 5.0') in text
    assert "elasticdl_agg_freshness_seconds 2.5" in text


# -- autoscaler --------------------------------------------------------


class _FakeRouter:
    def __init__(self, addrs, committed=1):
        self.state = FleetState(addrs, probe_interval=9999)
        self.committed = committed
        self.added = []
        self.removed = []

    def committed_view(self):
        return self.committed

    def add_replica(self, addr):
        self.state.add_replica(addr)
        self.added.append(addr)

    def remove_replica(self, addr):
        self.state.remove_replica(addr)
        self.removed.append(addr)

    def canary_addrs(self):
        return frozenset()


class _FakeSpawner:
    def __init__(self):
        self.spawned = []
        self.drained = []
        self.reaped = []

    def spawn(self, boot_version=None):
        addr = "spawned:%d" % len(self.spawned)
        self.spawned.append((addr, boot_version))
        return addr

    def drain(self, addr):
        self.drained.append(addr)

    def reap(self, addr, timeout=0):
        self.reaped.append(addr)


def _statz(queue_count, queue_sum_s, version=1, draining=False):
    return {
        "draining": draining,
        "models": {"m": {
            "version": version,
            "timing": {"batcher.queue_wait": {
                "count": queue_count,
                "mean_s": (queue_sum_s / queue_count)
                if queue_count else 0.0,
            }},
        }},
    }


def _feed(state, addr, count, total_s, now):
    state.note_probe_ok(addr, _statz(count, total_s), now)


def _scaler(router, spawner, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("scale_up_queue_ms", 25.0)
    kw.setdefault("scale_down_queue_ms", 2.0)
    kw.setdefault("breach_secs", 2.0)
    kw.setdefault("idle_secs", 5.0)
    kw.setdefault("cooldown_secs", 4.0)
    return FleetAutoscaler(router, spawner, **kw)


def test_probe_differencing_yields_recent_queue_wait():
    state = FleetState(["a:1"], probe_interval=1)
    _feed(state, "a:1", 100, 10.0, now=0)   # lifetime mean 100ms
    _feed(state, "a:1", 150, 10.5, now=1)   # recent: 0.5s / 50 = 10ms
    row = state.replica_row("a:1")
    assert row["queue_wait_recent_ms"] == pytest.approx(10.0)
    _feed(state, "a:1", 150, 10.5, now=2)   # idle interval
    assert state.replica_row("a:1")[
        "queue_wait_recent_ms"] == pytest.approx(0.0)
    _feed(state, "a:1", 5, 0.1, now=3)      # counter reset (restart)
    assert state.replica_row("a:1")["queue_wait_recent_ms"] is None


def test_autoscaler_grows_on_sustained_breach_only():
    router = _FakeRouter(["a:1"], committed=7)
    spawner = _FakeSpawner()
    scaler = _scaler(router, spawner)
    _feed(router.state, "a:1", 10, 1.0, now=0)    # 100ms recent wait
    scaler.tick(now=0.0)
    scaler.tick(now=1.0)
    assert spawner.spawned == []                  # not sustained yet
    scaler.tick(now=2.5)
    assert [a for a, _ in spawner.spawned] == ["spawned:0"]
    # Spawn boots pinned to the committed version; admitted to table.
    assert spawner.spawned[0][1] == 7
    assert router.added == ["spawned:0"]
    # Cooldown: the breach persists but no second spawn yet.
    scaler.tick(now=3.0)
    assert len(spawner.spawned) == 1


def test_autoscaler_respects_max_replicas():
    router = _FakeRouter(["a:1", "b:2", "c:3"])
    spawner = _FakeSpawner()
    scaler = _scaler(router, spawner, max_replicas=3)
    for addr in ("a:1", "b:2", "c:3"):
        _feed(router.state, addr, 10, 5.0, now=0)
    scaler.tick(now=0.0)
    scaler.tick(now=10.0)
    assert spawner.spawned == []


def test_autoscaler_shrinks_idle_fleet_after_drain_completes():
    router = _FakeRouter(["a:1", "b:2"])
    spawner = _FakeSpawner()
    scaler = _scaler(router, spawner)
    for now in (0.0, 6.0):
        for addr in ("a:1", "b:2"):
            _feed(router.state, addr, 10, 0.0, now=now)
        scaler.tick(now=now)
    assert spawner.drained == ["a:1"]  # idle for >= idle_secs
    assert router.removed == []        # NOT removed until drained
    # A forward admitted BEFORE the drain flag landed is still live
    # when the replica starts reporting draining.
    assert router.state.acquire(None, members={"a:1"}) == "a:1"
    router.state.note_probe_ok("a:1", _statz(10, 0.0, draining=True),
                               7.0)
    scaler.tick(now=8.0)
    assert router.removed == []        # in-flight forward pending
    router.state.forward_finished("a:1")
    scaler.tick(now=9.0)
    assert router.removed == ["a:1"]
    assert spawner.reaped == ["a:1"]


def test_autoscaler_reaps_crashed_spawn_and_replaces_it():
    router = _FakeRouter(["a:1", "spawned:0"], committed=3)
    spawner = _FakeSpawner()
    # The spawner "owns" spawned:0 and reports its process exited.
    spawner.spawned.append(("spawned:0", 3))
    spawner.addrs = lambda: ["spawned:0"]
    spawner.poll = lambda addr: 1  # crashed
    scaler = _scaler(router, spawner, min_replicas=2)
    _feed(router.state, "a:1", 10, 0.05, now=0)
    scaler.tick(now=0.0)
    # The corpse left the table (it no longer burns a max_replicas
    # slot) and the fleet dropped below min -> replaced immediately.
    assert router.removed == ["spawned:0"]
    assert spawner.reaped == ["spawned:0"]
    assert [a for a, _ in spawner.spawned[1:]] == ["spawned:1"]
    # An operator-provided replica (not in spawner.addrs) is never
    # reaped, however dead it looks.
    assert "a:1" not in router.removed


def test_canary_refused_in_routing_only_mode(tmp_path):
    base = tmp_path / "exports"
    _export_version(base, 1)
    replicas = [_Replica(base) for _ in range(2)]
    router = Router([r.addr for r in replicas],
                    probe_interval=0.05, poll_interval=0.1)
    router.start()  # routing-only: no export_dir
    try:
        assert _wait(lambda: len(router.state.routable(None)) == 2)
        # Fails FAST (routing-only mode runs no rollout thread — a
        # queued command must not wait out its whole timeout), and
        # says why.
        start = time.monotonic()
        out = router.start_canary(2, 0.5)
        assert time.monotonic() - start < 5.0
        assert not out.get("started")
        assert "coordination" in out["error"]
        assert "coordination" in router.external_rollout(2)["error"]
    finally:
        router.stop()
        for replica in replicas:
            replica.close()


def test_autoscaler_never_shrinks_an_operator_replica():
    """spawner.drain() is a no-op for a replica it does not own —
    'draining' one would force-remove a live operator-managed replica
    at drain_timeout.  Only spawner-owned replicas are candidates."""
    router = _FakeRouter(["op:1", "spawned:0"])
    spawner = _FakeSpawner()
    spawner.spawned.append(("spawned:0", 1))
    spawner.addrs = lambda: ["spawned:0"]
    spawner.poll = lambda addr: None  # alive
    scaler = _scaler(router, spawner)
    for now in (0.0, 6.0):
        for addr in ("op:1", "spawned:0"):
            _feed(router.state, addr, 10, 0.0, now=now)
        scaler.tick(now=now)
    assert spawner.drained == ["spawned:0"]


class _FakeFleet:
    """RouterClient-shaped stub for drive_rollout unit tests."""

    def __init__(self, canary_active=False, canary_requests=0):
        self.calls = []
        self.committed = 1
        self._active = canary_active
        self._requests = canary_requests

    def rollout(self, version, freshness=None):
        self.calls.append(("rollout", version))
        if self._active:
            return {"committed": False,
                    "error": "canary active (version 9); promote or "
                             "roll back first"}
        self.committed = version
        return {"committed": True, "committed_version": version}

    def canary_start(self, version, fraction, freshness=None):
        self.calls.append(("canary_start", version))
        if self._active:
            return {"started": False,
                    "error": "canary already active (version 9)"}
        return {"started": True}

    def canary_promote(self):
        self.calls.append(("promote",))
        self.committed = 9
        return {"promoted": True}

    def canary_rollback(self):
        self.calls.append(("rollback",))
        self._active = False
        return {"rolled_back": True}

    def status(self):
        return {"canary": {"cohorts": {"canary": {
            "requests": self._requests, "errors": 0}}}}

    def committed_version(self):
        return self.committed


def test_drive_rollout_recovers_from_stale_canary():
    from elasticdl_tpu.aggregation.main import drive_rollout

    # Plain-rollout path: refused by a standing canary -> rolled back
    # and retried, so one failed promote can't wedge every later
    # publish.
    fleet = _FakeFleet(canary_active=True)
    floor = drive_rollout(fleet, 12)
    assert ("rollback",) in fleet.calls
    assert fleet.calls.count(("rollout", 12)) == 2
    assert floor == 12
    # Canary path: 'already active' rolls the stale slice back first.
    fleet2 = _FakeFleet(canary_active=True)
    drive_rollout(fleet2, 12, canary_fraction=0.3,
                  canary_soak_secs=0.01)
    assert ("rollback",) in fleet2.calls
    assert fleet2.committed == 12


def test_canary_with_no_soak_evidence_rolls_back():
    from elasticdl_tpu.aggregation.main import drive_rollout

    # Zero canary traffic during the soak: no evidence, no promote.
    fleet = _FakeFleet(canary_requests=0)
    drive_rollout(fleet, 12, canary_fraction=0.3,
                  canary_soak_secs=0.01)
    assert ("rollback",) in fleet.calls
    assert ("promote",) not in fleet.calls
    # A shutdown mid-soak must not promote an unvalidated version.
    stop = threading.Event()
    stop.set()
    fleet2 = _FakeFleet(canary_requests=50)
    drive_rollout(fleet2, 12, canary_fraction=0.3,
                  canary_soak_secs=5.0, stop_event=stop)
    assert ("promote",) not in fleet2.calls
    assert ("rollback",) in fleet2.calls


def test_autoscaler_never_shrinks_below_min_or_drains_canary():
    router = _FakeRouter(["a:1", "b:2"])
    router.canary_addrs = lambda: frozenset(["a:1"])
    spawner = _FakeSpawner()
    scaler = _scaler(router, spawner, min_replicas=2)
    for now in (0.0, 6.0):
        for addr in ("a:1", "b:2"):
            _feed(router.state, addr, 10, 0.0, now=now)
        scaler.tick(now=now)
    assert spawner.drained == []  # min_replicas=2 floors the fleet
    scaler2 = _scaler(router, spawner, min_replicas=1)
    for now in (20.0, 26.0):
        scaler2.tick(now=now)
    # Only the non-canary replica is a shrink candidate.
    assert spawner.drained == ["b:2"]
