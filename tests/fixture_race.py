"""Seeded two-root shared-state race fixture for elastic-lint EL011 +
the runtime tracer's sampled attribute-access records.

``RacyTelemetryHub`` is the canonical lost-update shape: a flusher
daemon thread (``Thread(target=self._flush_loop)``) and executor
workers (``self._pool.submit(self._ingest, ...)``) both read-modify-
write the same attributes with NO lock — ``_total_reports`` via
``+=`` and ``_totals`` via in-place dict stores.  EL011 must flag both
attributes statically (two distinct roots, a write, empty guarded-by
intersection), and ``drive_race_from_two_threads`` exercises both
sides under the tracer so ``race_confirmations()`` witnesses the
counter race at runtime (the dict race stays static-only: instance
``__getattribute__`` instrumentation sees the attribute fetch, not the
``__setitem__`` behind it).

The lock exists but is never taken — exactly how these bugs look in
the wild (PR 4's PS servicer, PR 10's Timing snapshots).  This module
lives in tests/ (outside the lint gate) precisely so the seeded bug
stays seeded; ``fixture_race_clean.py`` is the counterpart that must
stay silent on both halves.
"""

import threading
from concurrent.futures import ThreadPoolExecutor


class RacyTelemetryHub:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=2)
        self._thread = None
        self._totals = {}
        self._total_reports = 0

    def start(self):
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True)
        self._thread.start()

    def submit_report(self, key):
        return self._pool.submit(self._ingest, key)

    def _flush_loop(self):
        while not self._stop.wait(0.01):
            self._flush_once()

    def _flush_once(self):
        # unguarded read-modify-write racing _ingest's: lost updates
        self._total_reports += 1
        self._totals["flushed"] = len(self._totals)

    def _ingest(self, key):
        self._total_reports += 1
        self._totals[key] = self._totals.get(key, 0) + 1

    def close(self):
        self._stop.set()
        self._pool.shutdown(wait=True)


def drive_race_from_two_threads(hub):
    """One flush pass on a dedicated thread, one ingest on a pool
    worker — two distinct thread idents touching the shared counters
    with no lock held, which is all the runtime sampler needs to
    confirm the race (no scheduling luck required).  The warm-up
    submit makes the pool worker exist FIRST: executors keep workers
    alive, so the freshly started flusher cannot be handed the pool
    thread's ident (the OS recycles idents of joined threads, which
    would make the two roots look like one thread)."""
    hub.submit_report("warm").result()
    flusher = threading.Thread(target=hub._flush_once)
    flusher.start()
    flusher.join()
    hub.submit_report("drill").result()
