"""Model-parallel checkpoint save/restore incl. mesh-layout resize."""

import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.models import transformer as tfm
from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.parallel.spmd_trainer import SPMDTrainer
from elasticdl_tpu.utils.checkpoint import CheckpointSaver

CFG = tfm.TransformerConfig(
    vocab_size=64, dim=32, num_heads=2, num_layers=2,
    max_seq_len=16, dtype="float32",
)


def make_trainer(mesh):
    def loss_fn(params, batch):
        tokens, _ = batch
        logits = tfm.forward(params, tokens, CFG, mesh=mesh)
        return tfm.next_token_loss(logits, tokens).mean()

    return SPMDTrainer(
        mesh,
        init_fn=lambda rng: tfm.init_params(rng, CFG),
        loss_fn=loss_fn,
        optimizer=optax.adam(1e-3),
        param_specs=tfm.param_specs(CFG),
        batch_spec=P("dp", "sp"),
        rng_seed=4,
    )


def test_spmd_checkpoint_restores_across_mesh_layouts(tmp_path):
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 64, size=(4, 16)).astype(np.int32)

    saver = CheckpointSaver(str(tmp_path))
    t1 = make_trainer(build_mesh(dp=2, tp=2, sp=2))
    for _ in range(2):
        t1.train_step((tokens, tokens))
    loss_before = float(t1.eval_loss((tokens, tokens)))
    t1.save_checkpoint(saver)

    # restore onto a DIFFERENT mesh layout (tp4, no sp): the elastic
    # resize path for model-parallel state
    t2 = make_trainer(build_mesh(dp=2, tp=4, sp=1))
    version = t2.restore_checkpoint(saver)
    assert version == 2
    loss_after = float(t2.eval_loss((tokens, tokens)))
    np.testing.assert_allclose(loss_before, loss_after, rtol=1e-4)

    # optimizer state survived too: the next step of both trainers
    # matches (Adam moments + counters were checkpointed, not reset)
    l1 = float(t1.train_step((tokens, tokens)))
    l2 = float(t2.train_step((tokens, tokens)))
    np.testing.assert_allclose(l1, l2, rtol=1e-4)
