"""In-process cluster harness (reference pattern:
elasticdl/python/tests/test_utils.py:301-472 — the whole distributed system
in one process, real gRPC on localhost ports)."""

from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.worker.master_client import MasterClient


def create_master(
    training_shards=None,
    evaluation_shards=None,
    records_per_task=32,
    num_epochs=1,
    evaluation_steps=0,
    metrics_factory=None,
    rendezvous=False,
    **task_kwargs,
):
    task_manager = TaskManager(
        training_shards=training_shards,
        evaluation_shards=evaluation_shards,
        records_per_task=records_per_task,
        num_epochs=num_epochs,
        **task_kwargs,
    )
    evaluation_service = None
    if evaluation_steps and metrics_factory:
        evaluation_service = EvaluationService(
            task_manager, metrics_factory, evaluation_steps=evaluation_steps
        )
    rdzv = RendezvousServer(grace_secs=0.1) if rendezvous else None
    master = Master(
        task_manager,
        rendezvous_server=rdzv,
        evaluation_service=evaluation_service,
    )
    master.prepare()
    return master


def create_master_client(master, worker_id=0):
    channel = grpc_utils.build_channel("localhost:%d" % master.port)
    grpc_utils.wait_for_channel_ready(channel)
    return MasterClient(channel, worker_id=worker_id)
