"""Cross-shard atomic sync push (VERDICT r1 #8).

The reference's sync PS buffers per shard
(python/ps/servicer.py:168-238), so with num_ps > 1 one shard could
accept a minibatch another shard rejected — the retry then double-applied
on the accepting shard.  The prepare/commit push closes that gap; this
matrix ports the reference's pserver_servicer_test semantics (staleness
windows, tolerance boundaries, interleaved workers) onto it.
"""

import time

import numpy as np

from tests.test_pserver import start_ps, stop_all


def _dense(val, n=4):
    return {"w": np.full(n, val, np.float32)}


def init_model(client, n=4):
    client.push_model({"w": np.zeros(n, np.float32)})


def test_unanimous_accept_commits_everywhere():
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=1,
    )
    try:
        client.push_model({"a": np.zeros(2, np.float32),
                           "b": np.zeros(2, np.float32)})
        accepted, version = client.push_gradients_atomic(
            {"a": np.ones(2, np.float32), "b": np.ones(2, np.float32)},
            version=0,
        )
        assert accepted and version == 1
        # both shards advanced in lockstep (empty prepares included)
        assert all(s._params.version == 1 for s in servicers)
        _, _, dense = client.pull_dense_parameters(-1)
        np.testing.assert_allclose(dense["a"], -1.0)
        np.testing.assert_allclose(dense["b"], -1.0)
    finally:
        stop_all(servers)


def test_one_shard_reject_aborts_all_shards():
    """The headline: a straggler's push must never half-apply.  Shard
    versions are desynced by hand; the shard still at the old version
    accepts, the advanced one rejects, and NEITHER applies."""
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=1, sync_version_tolerance=0,
    )
    try:
        client.push_model({"a": np.zeros(2, np.float32),
                           "b": np.zeros(2, np.float32)})
        servicers[0]._params.version = 5  # simulate drift
        before = {
            i: {k: v.copy() for k, v in s._params.dense.items()}
            for i, s in enumerate(servicers)
        }
        accepted, _ = client.push_gradients_atomic(
            {"a": np.ones(2, np.float32), "b": np.ones(2, np.float32)},
            version=0,  # stale for shard 0, fresh for shard 1
        )
        assert not accepted
        for i, s in enumerate(servicers):
            for k, v in s._params.dense.items():
                np.testing.assert_array_equal(v, before[i][k]), (i, k)
        # nothing left staged on either shard
        assert all(not s._staged for s in servicers)
    finally:
        stop_all(servers)


def test_tolerance_boundary_exact():
    """grad_version == version - tolerance is ACCEPTED; one older is
    rejected (reference tolerance boundary semantics)."""
    client, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=1, sync_version_tolerance=2,
    )
    try:
        init_model(client)
        for v in range(3):
            accepted, _ = client.push_gradients_atomic(
                _dense(0.1), version=v
            )
            assert accepted
        # server version is now 3; tolerance 2 -> floor is version 1:
        # exactly-at-floor is accepted
        accepted, _ = client.push_gradients_atomic(_dense(0.1), version=1)
        assert accepted
        # that apply moved the server to 4 (floor 2): version 1 is now
        # one below the floor and must be rejected
        accepted, _ = client.push_gradients_atomic(_dense(0.1), version=1)
        assert not accepted
    finally:
        stop_all(servers)


def test_stale_beyond_tolerance_rejected():
    client, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=1, sync_version_tolerance=1,
    )
    try:
        init_model(client)
        for v in range(3):
            client.push_gradients_atomic(_dense(0.1), version=v)
        # server at 3, floor = 2: version 1 is too stale
        accepted, _ = client.push_gradients_atomic(_dense(0.1), version=1)
        assert not accepted
    finally:
        stop_all(servers)


def test_interleaved_workers_sync_buffer():
    """Two workers, grads_to_wait=2: both commits land in the buffer and
    ONE averaged apply advances the version."""
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=2, sync_version_tolerance=0,
    )
    try:
        client.push_model({"a": np.zeros(2, np.float32),
                           "b": np.zeros(2, np.float32)})
        a1, v1 = client.push_gradients_atomic(
            {"a": np.full(2, 2.0, np.float32),
             "b": np.full(2, 2.0, np.float32)}, version=0,
        )
        assert a1 and v1 == 0  # buffered, not yet applied
        a2, v2 = client.push_gradients_atomic(
            {"a": np.full(2, 4.0, np.float32),
             "b": np.full(2, 4.0, np.float32)}, version=0,
        )
        assert a2 and v2 == 1  # second commit flushed the buffer
        _, _, dense = client.pull_dense_parameters(-1)
        # averaged: (2+4)/2 = 3, lr 1.0 -> w = -3
        np.testing.assert_allclose(dense["a"], -3.0)
        np.testing.assert_allclose(dense["b"], -3.0)
    finally:
        stop_all(servers)


def test_sparse_gradients_route_and_commit_atomically():
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=1,
    )
    try:
        client.push_model(
            {"w": np.zeros(2, np.float32)},
            embedding_infos=[
                {"name": "emb", "dim": 2, "initializer": "zeros"}
            ],
        )
        ids = np.array([0, 1, 2, 3], np.int64)
        grads = np.ones((4, 2), np.float32)
        accepted, _ = client.push_gradients_atomic(
            {"w": np.ones(2, np.float32)}, {"emb": (grads, ids)},
            version=0,
        )
        assert accepted
        rows = client.pull_embedding_vectors("emb", ids)
        np.testing.assert_allclose(rows, -1.0)  # applied on both shards
    finally:
        stop_all(servers)


def test_abandoned_prepare_is_purged():
    """A worker that dies between prepare and commit must not leak staged
    state forever."""
    client, servicers, servers = start_ps(
        num_ps=1, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=1,
    )
    try:
        init_model(client)
        from elasticdl_tpu.proto import elastic_pb2 as pb
        from elasticdl_tpu.utils import tensor_codec

        model = tensor_codec.model_to_pb(dense=_dense(1.0), version=0)
        servicers[0].prepare_gradients(
            pb.PrepareGradientsRequest(txn_id="dead-worker",
                                       gradients=model)
        )
        assert "dead-worker" in servicers[0]._staged
        servicers[0]._staged_ttl = 0.0
        time.sleep(0.01)
        # any later prepare triggers the purge
        servicers[0].prepare_gradients(
            pb.PrepareGradientsRequest(txn_id="live", gradients=model)
        )
        assert "dead-worker" not in servicers[0]._staged
    finally:
        stop_all(servers)


def test_async_mode_atomic_push_applies_per_push():
    """The atomic client path degrades gracefully against an async PS:
    every commit applies immediately, version++ per push."""
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=True,
    )
    try:
        client.push_model({"a": np.zeros(2, np.float32),
                           "b": np.zeros(2, np.float32)})
        for i in range(3):
            accepted, version = client.push_gradients_atomic(
                {"a": np.ones(2, np.float32),
                 "b": np.ones(2, np.float32)}, version=i,
            )
            assert accepted
        assert all(s._params.version == 3 for s in servicers)
    finally:
        stop_all(servers)


def test_ttl_evicted_txn_fails_the_push_not_silently():
    """If a shard TTL-evicted the staged txn before commit, the push
    must report failure (worker retries) instead of silently losing the
    minibatch on that shard."""
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="sgd", opt_args="learning_rate=1.0",
        use_async=False, grads_to_wait=1,
    )
    try:
        client.push_model({"a": np.zeros(2, np.float32),
                           "b": np.zeros(2, np.float32)})

        # Servicer side: commit for an evicted txn reports accepted=False.
        from elasticdl_tpu.proto import elastic_pb2 as pb

        res = servicers[0].commit_gradients(
            pb.CommitGradientsRequest(txn_id="gone", commit=True)
        )
        assert not res.accepted

        # Client side: evict shard 0's staged txn between the client's
        # prepare and commit phases (hook the stub so the sweep happens
        # exactly at commit-send time), and the push must report failure.
        orig = client._stubs[0].commit_gradients

        class EvictingCommit:
            def future(self, req):
                servicers[0]._staged.clear()  # simulate TTL sweep
                return orig.future(req)

        client._stubs[0].commit_gradients = EvictingCommit()
        accepted, _ = client.push_gradients_atomic(
            {"a": np.ones(2, np.float32), "b": np.ones(2, np.float32)},
            version=0,
        )
        assert not accepted
    finally:
        stop_all(servers)
