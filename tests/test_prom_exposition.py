"""Strict Prometheus exposition-format conformance over EVERY
renderer (ISSUE 14 satellite): master, multi-tenant master, serving
replica, fleet router, PS shard.

The parser here is deliberately unforgiving — line grammar from the
exposition-format spec, label-escaping round-trip, histogram bucket
monotonicity (cumulative nondecreasing, ascending ``le``, the
mandatory ``+Inf`` row equal to ``_count``), and no duplicate series
(metric + label-set unique per scrape).  A renderer that emits
something a real scraper would mis-parse fails HERE, not in some
dashboard three weeks later.

Also the registry cross-checks (elastic-lint EL010's runtime halves):
every emitted name must be declared in utils/metric_registry.py, and
every ``elasticdl_*`` token in the docs' metric tables must be
declared too — docs cannot drift from the registry.
"""

import glob
import os
import re

import pytest

from elasticdl_tpu.utils import hist, metric_registry
from elasticdl_tpu.utils.prom import (
    fleet_to_prometheus,
    multitenant_to_prometheus,
    ps_to_prometheus,
    serving_to_prometheus,
    to_prometheus,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LINE_RE = re.compile(
    r"^(?P<name>%s)(?:\{(?P<labels>[^{}]*)\})? (?P<value>\S+)$"
    % _NAME)
_LABEL_RE = re.compile(
    r'^(?P<name>%s)="(?P<value>(?:[^"\\\n]|\\\\|\\"|\\n)*)"$' % _NAME)


def parse_exposition(text):
    """Parse one scrape strictly; returns [(name, labels_dict, value)]
    and raises AssertionError on any grammar violation."""
    assert text.endswith("\n"), "scrape must end with a newline"
    samples = []
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        assert m, "line %d fails exposition grammar: %r" % (
            lineno, line)
        labels = {}
        raw = m.group("labels")
        if raw is not None:
            assert raw != "", "empty label braces: %r" % line
            # split on commas NOT inside quotes
            parts = re.findall(
                r'(?:[^,"]|"(?:[^"\\]|\\.)*")+', raw)
            assert ",".join(parts) == raw, (
                "label split mismatch: %r" % line)
            for part in parts:
                lm = _LABEL_RE.match(part)
                assert lm, "bad label pair %r in %r" % (part, line)
                assert lm.group("name") not in labels, (
                    "duplicate label %r in %r" % (lm.group("name"),
                                                  line))
                labels[lm.group("name")] = lm.group("value")
        value = m.group("value")
        if value not in ("+Inf", "-Inf", "NaN"):
            float(value)  # must parse
        samples.append((m.group("name"), labels, value))
    return samples


def check_scrape(text):
    """Full conformance: grammar, duplicate series, histogram
    invariants, registry membership.  Returns the parsed samples."""
    samples = parse_exposition(text)
    seen = set()
    for name, labels, _ in samples:
        key = (name, tuple(sorted(labels.items())))
        assert key not in seen, "duplicate series %s%r" % (name,
                                                           labels)
        seen.add(key)
    _check_histograms(samples)
    for name, _, _ in samples:
        assert metric_registry.is_declared(name), (
            "series %r not declared in utils/metric_registry.py"
            % name)
    return samples


def _check_histograms(samples):
    by_series = {}
    for name, labels, value in samples:
        by_series.setdefault(name, []).append((labels, value))
    for name in {n[: -len("_bucket")] for n, _, _ in samples
                 if n.endswith("_bucket")}:
        buckets = {}
        for labels, value in by_series.get(name + "_bucket", []):
            group = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"))
            buckets.setdefault(group, []).append(
                (labels["le"], value))
        for group, rows in buckets.items():
            les = [le for le, _ in rows]
            assert les[-1] == "+Inf", (
                "%s%r: last bucket must be +Inf" % (name, group))
            finite = [float(le) for le in les[:-1]]
            assert finite == sorted(finite), (
                "%s%r: le values not ascending" % (name, group))
            counts = [float(v) for _, v in rows]
            assert counts == sorted(counts), (
                "%s%r: cumulative bucket counts decrease"
                % (name, group))
            # _count must exist for the same label group and equal
            # the +Inf bucket; _sum must exist.
            count_rows = {
                tuple(sorted(labels.items())): float(v)
                for labels, v in by_series.get(name + "_count", [])
            }
            sum_rows = {
                tuple(sorted(labels.items()))
                for labels, _ in by_series.get(name + "_sum", [])
            }
            assert group in count_rows, "%s%r: missing _count" % (
                name, group)
            assert count_rows[group] == counts[-1], (
                "%s%r: +Inf bucket != _count" % (name, group))
            assert group in sum_rows, "%s%r: missing _sum" % (name,
                                                              group)


def _snap(values):
    h = hist.Histogram()
    for v in values:
        h.observe(v)
    return h.snapshot()


def _telemetry():
    job_hist = _snap([0.01, 0.02, 0.02, 0.4])
    return {
        "workers": {
            1: {"steps_per_sec": 10.0, "sync_fraction": 0.25,
                "push_staleness": 1.0, "window_size": 8.0,
                "steps_done": 100, "fresh": True, "age_secs": 1.0,
                "straggler": False, "step_p50_ms": 12.0},
            2: {"steps_per_sec": 2.0, "sync_fraction": None,
                "push_staleness": None, "window_size": None,
                "steps_done": 40, "fresh": True, "age_secs": 2.0,
                "straggler": True, "step_p50_ms": 48.0},
        },
        "job": {"steps_per_sec": 12.0, "workers_reporting": 2,
                "step_hist": job_hist,
                "step_time_p50_ms": 20.0, "step_time_p99_ms": 380.0},
    }


_SLO = {
    "rules": {
        "agg_freshness": {"ok": True, "breach_total": 2},
        "stragglers": {"ok": False, "breach_total": 1},
    },
}


def master_status():
    return {
        "tasks": {"todo": 3, "doing": 1, "epoch": 0,
                  "completed": {"training": 5, "evaluation": 1},
                  "failed": {"training": 1}},
        "finished": False,
        "workers": {"live": [1, 2]},
        "rendezvous": {"epoch": 4, "world": ["w1", "w2"]},
        "exec_counters": {"batch_count": 12},
        "telemetry": _telemetry(),
        "ps": {"shards": {0: {"generation": 2, "version": 9,
                              "durable_version": 8}},
               "commit_mark": 8},
        "rpc_hists": {"get_task": _snap([0.001, 0.002]),
                      "report_batch_done": _snap([0.0005] * 10)},
        "slo": _SLO,
    }


def multitenant_status():
    return {
        "sched": {"pool_workers": 4, "pending_jobs": 1,
                  "decisions": {"admit": 2, "assign": 4},
                  "workers_assigned": {"job-a": 3, "job-b": 1},
                  "hists": {"tick": _snap([0.002, 0.004])}},
        "jobs": {
            "job-a": {
                "state": "running",
                "tasks": {"todo": 1, "doing": 2, "epoch": 0,
                          "completed": {"training": 7},
                          "failed": {}},
                "finished": False,
                "telemetry": _telemetry(),
                "exec_counters": {"batch_count": 5},
                "rendezvous": {"epoch": 2, "world": ["w1"]},
            },
            'job-"b"\n': {  # hostile name: escaping must hold
                "state": "pending",
                "tasks": {"todo": 0, "doing": 0, "epoch": 0,
                          "completed": {}, "failed": {}},
                "finished": False,
                "telemetry": {"workers": {}, "job": {}},
                "exec_counters": {},
            },
        },
        "workers": {"live": [1, 2, 3, 4]},
        "slo": _SLO,
    }


def serving_status():
    return {
        "draining": False,
        "models": {
            "m": {
                "version": 7,
                "counters": {"batcher.requests": 100,
                             "batcher.batches": 20,
                             "batcher.rows": 90},
                "timing": {"batcher.queue_wait":
                           {"total_s": 0.5, "count": 100,
                            "mean_s": 0.005}},
                "mean_batch_occupancy": 4.5,
                "queue_wait_recent_ms": 3.25,
                "hists": {
                    "batcher.queue_wait": _snap([0.004] * 100),
                    "batcher.execute": _snap([0.02] * 20),
                },
                "emb_cache": {"bytes": 1024, "rows": 8,
                              "evicted_rows": 2, "hit_ratio": 0.75},
            },
        },
        "slo": _SLO,
    }


def fleet_status():
    return {
        "committed_version": 7,
        "replicas": {
            "127.0.0.1:9001": {"healthy": True, "serving_version": 7,
                               "inflight": 2, "queue_wait_ms": 4.0,
                               "queue_wait_recent_ms": 2.0},
            "127.0.0.1:9002": {"healthy": False, "serving_version": 6,
                               "inflight": 0, "queue_wait_ms": None,
                               "queue_wait_recent_ms": None},
        },
        "counters": {"router.forwarded": 500, "router.retried": 1},
        "latency_hists": {"127.0.0.1:9001": _snap([0.01] * 50)},
        "canary": {
            "active": True, "version": 8, "fraction": 0.25,
            "replicas": ["127.0.0.1:9002"],
            "cohorts": {
                "baseline": {"requests": 400, "keyed_requests": 100,
                             "errors": 1, "latency_ms_sum": 4000.0,
                             "model_version": 7,
                             "latency_hist": _snap([0.01] * 400)},
                "canary": {"requests": 100, "keyed_requests": 100,
                           "errors": 0, "latency_ms_sum": 900.0,
                           "model_version": 8,
                           "latency_hist": _snap([0.009] * 100)},
            },
        },
        "aggregation": {"freshness_seconds": 1.25, "version": 8},
        "slo": _SLO,
    }


def ps_status():
    return {
        "ps_id": 0, "num_ps": 2, "version": 9, "generation": 2,
        "durable_version": 8, "initialized": True,
        "counters": {"push_accepted": 50, "pull_dense": 10},
        "hists": {"ps.push_handle": _snap([0.002] * 50),
                  "ps.pull_dense": _snap([0.004] * 10),
                  "ps.pull_embedding": _snap([0.001] * 5)},
        "slo": _SLO,
    }


RENDERERS = [
    ("master", to_prometheus, master_status),
    ("multitenant", multitenant_to_prometheus, multitenant_status),
    ("serving", serving_to_prometheus, serving_status),
    ("fleet", fleet_to_prometheus, fleet_status),
    ("ps", ps_to_prometheus, ps_status),
]


@pytest.mark.parametrize("name,renderer,status",
                         RENDERERS, ids=[r[0] for r in RENDERERS])
def test_renderer_conforms(name, renderer, status):
    samples = check_scrape(renderer(status()))
    assert samples, "renderer %s emitted nothing" % name


def test_histograms_render_on_every_latency_surface():
    """The tentpole invariant: every latency series on every /metrics
    surface has a native histogram a scraper can take p99 of."""
    expectations = [
        (to_prometheus(master_status()),
         ["elasticdl_master_rpc_handle_seconds_bucket",
          "elasticdl_job_step_time_seconds_bucket"]),
        (multitenant_to_prometheus(multitenant_status()),
         ["elasticdl_sched_decision_seconds_bucket",
          "elasticdl_job_step_time_seconds_bucket"]),
        (serving_to_prometheus(serving_status()),
         ["elasticdl_serving_queue_wait_seconds_bucket",
          "elasticdl_serving_execute_seconds_bucket"]),
        (fleet_to_prometheus(fleet_status()),
         ["elasticdl_fleet_replica_latency_seconds_bucket",
          "elasticdl_fleet_cohort_latency_seconds_bucket"]),
        (ps_to_prometheus(ps_status()),
         ["elasticdl_ps_push_handle_seconds_bucket",
          "elasticdl_ps_pull_dense_seconds_bucket",
          "elasticdl_ps_pull_embedding_seconds_bucket"]),
    ]
    for text, names in expectations:
        for metric in names:
            assert metric + "{" in text or metric + " " in text, (
                "missing histogram %s" % metric)


def test_label_escaping_round_trips_hostile_job_name():
    text = multitenant_to_prometheus(multitenant_status())
    samples = parse_exposition(text)
    hostile = [labels for _, labels, _ in samples
               if "job" in labels and "\\" in repr(labels["job"])]
    assert any(labels["job"] == 'job-\\"b\\"\\n' for labels in hostile)


def test_parser_rejects_bad_lines():
    with pytest.raises(AssertionError):
        parse_exposition("elasticdl_x{le=0.1} 3\n")  # unquoted label
    with pytest.raises(AssertionError):
        parse_exposition("3elasticdl_x 1\n")  # bad metric name
    with pytest.raises(AssertionError):
        parse_exposition("elasticdl_x 1")  # missing trailing newline
    with pytest.raises(AssertionError):
        parse_exposition('elasticdl_x{a="1",a="2"} 1\n')  # dup label


def test_parser_rejects_broken_histogram():
    # cumulative counts must be nondecreasing
    bad = ('elasticdl_h_bucket{le="0.1"} 5\n'
           'elasticdl_h_bucket{le="+Inf"} 3\n'
           'elasticdl_h_sum 1.0\n'
           'elasticdl_h_count 3\n')
    with pytest.raises(AssertionError):
        _check_histograms(parse_exposition(bad))


def test_duplicate_series_detected():
    with pytest.raises(AssertionError):
        check_scrape("elasticdl_workers_live 1\n"
                     "elasticdl_workers_live 2\n")


# -- registry cross-checks ---------------------------------------------------

def test_docs_metric_tables_match_registry():
    tokens = set()
    for path in glob.glob(os.path.join(REPO, "docs", "*.md")):
        with open(path, encoding="utf-8") as f:
            tokens.update(re.findall(r"elasticdl_[a-z0-9_]+",
                                     f.read()))
    undeclared = sorted(
        t for t in tokens
        # Trailing-underscore tokens are brace-expansion shorthand
        # ("elasticdl_slo_{ok,breach_total}"): the prefix itself is
        # not a series.
        if not t.endswith("_")
        and not metric_registry.is_declared(t)
        and not t.startswith("elasticdl_tpu")  # the package name
    )
    assert not undeclared, (
        "docs mention series not in utils/metric_registry.py: %s"
        % undeclared)


def test_registry_has_no_blank_help():
    for name, meta in metric_registry.METRICS.items():
        assert meta["help"].strip(), "registry entry %r has no help" % (
            name)
