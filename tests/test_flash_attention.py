"""Pallas flash attention vs the jnp reference (interpret mode on CPU)."""

from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.flash_attention import (
    _attention_ref,
    flash_attention,
)


def make_qkv(b=2, h=2, t=256, d=64, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, h, t, d)
    return tuple(
        jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = make_qkv()
    ref = _attention_ref(q, k, v, causal, q.shape[-1] ** -0.5)
    out = flash_attention(q, k, v, causal=causal, block_q=128,
                          block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_multi_k_block_grid(causal):
    # t=1024 with the kernel's 512-max tiling makes the K grid dimension
    # 2 — exercising the scratch carry across ki, the pl.when
    # init/finish gating, the causal dead-block skip, and the clamped
    # kv_index DMA dedup, none of which engage when the grid is 1x1.
    q, k, v = make_qkv(b=1, h=1, t=1024, d=64, seed=3)
    ref = _attention_ref(q, k, v, causal, q.shape[-1] ** -0.5)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_small_blocks_fall_back():
    # block_k != 128 cannot lane-align with the kernel's stats tiles;
    # the wrapper must take the dense reference path (and still be
    # numerically right).
    q, k, v = make_qkv(t=128, d=64)
    ref = _attention_ref(q, k, v, True, q.shape[-1] ** -0.5)
    with mock.patch(
        "elasticdl_tpu.ops.flash_attention._flash",
        side_effect=AssertionError("kernel must not run for block_k=64"),
    ):
        out = flash_attention(q, k, v, block_q=64, block_k=64,
                              interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_grad_matches_reference():
    q, k, v = make_qkv(t=128)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, interpret=True).sum()

    def loss_ref(q, k, v):
        return _attention_ref(q, k, v, True, q.shape[-1] ** -0.5).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_unfriendly_shapes_fall_back():
    q, k, v = make_qkv(t=100, d=48)
    out = flash_attention(q, k, v)  # no crash: reference path
    assert out.shape == q.shape


def test_partial_matches_reference_stats():
    """flash_attention_partial returns (acc, l, m) that normalize to the
    reference output — the ring-fold building block."""
    from elasticdl_tpu.ops.flash_attention import (
        _partial_ref,
        flash_attention_partial,
    )

    q, k, v = make_qkv(t=128)
    for causal in (True, False):
        acc, l, m = flash_attention_partial(
            q, k, v, causal=causal, interpret=True
        )
        acc_r, l_r, m_r = _partial_ref(
            q, k, v, causal, q.shape[-1] ** -0.5, 0
        )
        out = acc / np.maximum(np.asarray(l), 1e-30)[..., None]
        out_r = acc_r / np.maximum(np.asarray(l_r), 1e-30)[..., None]
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_r),
                                   rtol=2e-5, atol=2e-5)
        ref = _attention_ref(q, k, v, causal, q.shape[-1] ** -0.5)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("t", [256, 384])
def test_pallas_bwd_matches_reference(causal, t, monkeypatch):
    """The default backward is the Pallas kernel pair (dq; dk/dv) —
    it must be the path taken and match reference gradients.  t=384
    forces tile=128 -> a 3x3 block grid, exercising the cross-step
    scratch accumulation and the causal-clamped index maps (t=256 is
    a single-block grid where init/finish coincide)."""
    import elasticdl_tpu.ops.flash_attention as fa

    called = {}
    orig = fa._pallas_bwd

    def spy(*args, **kwargs):
        called["yes"] = True
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "_pallas_bwd", spy)
    q, k, v = make_qkv(t=t)

    def loss_flash(q, k, v):
        return (
            fa.flash_attention(q, k, v, causal=causal,
                               interpret=True) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            fa._attention_ref(q, k, v, causal,
                              q.shape[-1] ** -0.5) ** 2
        ).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert called.get("yes"), "pallas bwd was not invoked"
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_xla_bwd_escape_hatch_matches(monkeypatch):
    """ELASTICDL_FLASH_BWD=xla routes through the block-recompute scan
    (the fallback while a relay can't compile the bwd kernels)."""
    import elasticdl_tpu.ops.flash_attention as fa

    monkeypatch.setenv("ELASTICDL_FLASH_BWD", "xla")
    called = {}
    orig = fa._blockwise_bwd

    def spy(*args, **kwargs):
        called["yes"] = True
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "_blockwise_bwd", spy)
    q, k, v = make_qkv(t=256)

    def loss_flash(q, k, v):
        return (fa.flash_attention(q, k, v, interpret=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (
            fa._attention_ref(q, k, v, True, q.shape[-1] ** -0.5) ** 2
        ).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    assert called.get("yes"), "xla block-recompute bwd was not invoked"
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("causal", [True, False])
def test_partial_stats_bwd_matches_dense(causal, monkeypatch):
    """The partial custom-vjp's stats-based blockwise backward must give
    the same (acc, l, m) cotangent pullbacks as differentiating the
    dense reference — including the l/m cotangents a ring fold
    produces."""
    import elasticdl_tpu.ops.flash_attention as fa

    called = {}
    orig = fa._partial_stats_bwd

    def spy(*args, **kwargs):
        called["yes"] = True
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "_partial_stats_bwd", spy)

    q, k, v = make_qkv(b=1, h=2, t=512, d=64, seed=7)
    scale = q.shape[-1] ** -0.5
    rng = np.random.RandomState(1)
    cot = (
        jnp.asarray(rng.randn(1, 2, 512, 64).astype(np.float32)),
        jnp.asarray(rng.randn(1, 2, 512).astype(np.float32)),
        jnp.asarray(rng.randn(1, 2, 512).astype(np.float32)),
    )

    outs_d, vjp_d = jax.vjp(
        lambda q, k, v: fa._partial_ref(q, k, v, causal, scale, 0),
        q, k, v,
    )
    outs_f, vjp_f = jax.vjp(
        lambda q, k, v: fa.flash_attention_partial(
            q, k, v, causal=causal, interpret=True
        ),
        q, k, v,
    )
    grads_f = vjp_f(cot)
    assert called.get("yes"), "stats-based partial bwd was not invoked"
    for a, b in zip(outs_d, outs_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
    for a, b in zip(vjp_d(cot), grads_f):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=2e-3)


def test_transformer_hits_flash_path(monkeypatch):
    """With ELASTICDL_FLASH=interpret the flagship transformer's
    attention goes through the Pallas kernel (VERDICT r1: the kernel was
    an orphan nothing called)."""
    import elasticdl_tpu.ops.flash_attention as fa
    from elasticdl_tpu.models import transformer as tfm

    monkeypatch.setenv("ELASTICDL_FLASH", "interpret")
    called = {}
    orig = fa._flash_forward

    def spy(*args, **kwargs):
        called["yes"] = True
        return orig(*args, **kwargs)

    monkeypatch.setattr(fa, "_flash_forward", spy)
    cfg = tfm.TransformerConfig(
        vocab_size=128, dim=128, num_heads=2, num_layers=2,
        max_seq_len=128, dtype="float32",
    )
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, size=(2, 128)), jnp.int32
    )
    logits = tfm.forward(params, tokens, cfg, mesh=None)
    assert called.get("yes"), "transformer did not reach the flash kernel"
    # and the flash-backed forward matches the jnp-backed forward
    monkeypatch.setenv("ELASTICDL_FLASH", "off")
    logits_ref = tfm.forward(params, tokens, cfg, mesh=None)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits_ref),
                               rtol=2e-4, atol=2e-4)
