"""Pallas flash attention vs the jnp reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.flash_attention import (
    _attention_ref,
    flash_attention,
)


def make_qkv(b=2, h=2, t=256, d=64, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, h, t, d)
    return tuple(
        jnp.asarray(rng.randn(*shape).astype(np.float32)) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = make_qkv()
    ref = _attention_ref(q, k, v, causal, q.shape[-1] ** -0.5)
    out = flash_attention(q, k, v, causal=causal, block_q=128,
                          block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_small_blocks():
    q, k, v = make_qkv(t=128, d=64)
    ref = _attention_ref(q, k, v, True, q.shape[-1] ** -0.5)
    out = flash_attention(q, k, v, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_grad_matches_reference():
    q, k, v = make_qkv(t=128)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, interpret=True).sum()

    def loss_ref(q, k, v):
        return _attention_ref(q, k, v, True, q.shape[-1] ** -0.5).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_unfriendly_shapes_fall_back():
    q, k, v = make_qkv(t=100, d=48)
    out = flash_attention(q, k, v)  # no crash: reference path
    assert out.shape == q.shape
