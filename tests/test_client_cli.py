"""Client CLI: zoo tooling, k8s rendering, job arg plumbing."""

import os

from elasticdl_tpu.client.main import _split_args, _zoo_init, main


def test_zoo_init_scaffolds_project(tmp_path):
    path = str(tmp_path / "zoo")

    class A:
        pass

    args = A()
    args.path = path
    assert _zoo_init(args) == 0
    assert os.path.exists(os.path.join(path, "my_model.py"))
    assert os.path.exists(os.path.join(path, "Dockerfile"))
    # scaffolded model module must satisfy the zoo contract
    import importlib.util

    spec_mod = importlib.util.spec_from_file_location(
        "my_model", os.path.join(path, "my_model.py")
    )
    module = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(module)
    spec = module.model_spec()
    assert spec.name == "my_model"


def test_zoo_build_and_push_shell_out(tmp_path, monkeypatch):
    """``zoo build/push`` drive the docker CLI (the reference drives
    docker-py programmatically, elasticdl_client/api.py:52-78; the TPU
    build shells out instead).  A fake ``docker`` on PATH records the
    exact invocations and its exit code must propagate — this path had
    zero coverage (VERDICT r4 missing #2)."""
    import stat

    from elasticdl_tpu.client.main import main

    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    calls = tmp_path / "docker_calls.log"
    fake = bin_dir / "docker"
    fake.write_text(
        "#!/bin/sh\necho \"$@\" >> %s\nexit ${DOCKER_FAKE_RC:-0}\n"
        % calls
    )
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv(
        "PATH", "%s:%s" % (bin_dir, os.environ["PATH"]))

    zoo = tmp_path / "zoo"
    assert main(["zoo", "init", str(zoo)]) == 0
    assert main(["zoo", "build", str(zoo),
                 "--image", "repo/img:v1"]) == 0
    assert main(["zoo", "push", "--image", "repo/img:v1"]) == 0
    lines = calls.read_text().splitlines()
    assert lines == [
        "build -t repo/img:v1 %s" % zoo,
        "push repo/img:v1",
    ]

    monkeypatch.setenv("DOCKER_FAKE_RC", "3")
    assert main(["zoo", "push", "--image", "repo/img:v1"]) == 3


def test_split_args_passthrough():
    cli, rest = _split_args([
        "--platform", "k8s", "--image", "img:1",
        "--model_zoo", "mnist", "--batch_size", "64",
    ])
    assert cli.platform == "k8s" and cli.image == "img:1"
    assert rest == ["--model_zoo", "mnist", "--batch_size", "64"]


def test_k8s_manifest_renders_master_pod():
    from elasticdl_tpu.client.k8s_submit import render_manifests

    manifest = render_manifests(
        ["--job_name", "myjob", "--model_zoo", "mnist"],
        image="img:2", namespace="ml",
    )
    assert '"name": "myjob-master"' in manifest
    assert '"namespace": "ml"' in manifest
    assert '"image": "img:2"' in manifest
    assert '"--model_zoo"' in manifest

    # --volume in the job args mounts on the master pod too
    manifest = render_manifests(
        ["--job_name", "myjob", "--volume",
         "claim_name=data,mount_path=/data"],
        image="img:2",
    )
    assert '"claimName": "data"' in manifest
    assert '"mountPath": "/data"' in manifest


def test_k8s_service_port_follows_job_port():
    """An explicit --port parameterizes the Service port/targetPort so
    worker pods dialing the service DNS name reach the master
    (ADVICE r3: it used to stay hard-coded at 50001)."""
    from elasticdl_tpu.client.k8s_submit import build_manifests

    _pod, svc = build_manifests(
        ["--job_name", "j", "--port", "6100"], image="i")
    assert svc["spec"]["ports"] == [{"port": 6100, "targetPort": 6100}]
    _pod, svc = build_manifests(["--job_name", "j"], image="i")
    assert svc["spec"]["ports"] == [
        {"port": 50001, "targetPort": 50001}]


def test_cli_help_and_unknown():
    assert main([]) == 1


def test_cli_serve_end_to_end(tmp_path):
    """`elasticdl-tpu serve` over a fresh export: the full
    export -> serve -> predict loop through the CLI."""
    import json
    import subprocess
    import sys
    import time
    import urllib.request

    import numpy as np

    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.utils.grpc_utils import find_free_port

    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x @ p["w"],
        {"w": np.eye(3, dtype=np.float32) * 2.0},
        np.zeros((1, 3), np.float32),
        model_name="srv",
        platforms=("cpu",),
    )
    port = find_free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "elasticdl_tpu.client.main", "serve",
         "--export_dir", str(tmp_path / "e"), "--port", str(port),
         "--host", "127.0.0.1"],
        env={**os.environ, "ELASTICDL_TPU_PLATFORM": "cpu",
             "JAX_PLATFORMS": "cpu"},
    )
    base = "http://127.0.0.1:%d/v1/models/srv" % port
    try:
        deadline = time.time() + 60
        while True:
            try:
                with urllib.request.urlopen(base, timeout=5) as resp:
                    meta = json.loads(resp.read())
                break
            except OSError:
                if time.time() > deadline:
                    raise
                assert proc.poll() is None, "server died"
                time.sleep(0.3)
        assert meta["metadata"]["model_name"] == "srv"
        req = urllib.request.Request(
            base + ":predict",
            data=json.dumps({"instances": [[1, 2, 3]]}).encode(),
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        np.testing.assert_allclose(out["predictions"], [[2.0, 4.0, 6.0]])
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def test_inspect_export_and_checkpoint(tmp_path, capsys):
    """`elasticdl-tpu inspect` summarizes servable exports (incl.
    versioned + quantized) and checkpoint dirs."""
    import numpy as np

    from elasticdl_tpu.client.main import main as cli_main
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.utils.checkpoint import CheckpointSaver

    base = str(tmp_path / "models")
    rng = np.random.RandomState(0)
    for version in (1, 3):
        export_servable(
            os.path.join(base, str(version)),
            lambda p, x: x @ p["w"],
            {"w": rng.randn(128, 64).astype(np.float32)},
            np.zeros((1, 128), np.float32), model_name="m",
            version=version, platforms=("cpu",), quantize="int8",
        )
    assert cli_main(["inspect", base]) == 0
    out = capsys.readouterr().out
    assert "versions on disk: [1, 3]" in out
    assert "int8-quantized: w" in out
    assert "model_name: m" in out

    ckpt = str(tmp_path / "ckpt")
    saver = CheckpointSaver(ckpt)
    saver.save(7, dense={"w": np.ones(4, np.float32),
                         "opt/w": np.zeros(4, np.float32)})
    assert cli_main(["inspect", ckpt]) == 0
    out = capsys.readouterr().out
    assert "version-" in out and "latest loadable: version 7" in out

    assert cli_main(["inspect", str(tmp_path / "nope")]) == 1
