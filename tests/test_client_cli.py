"""Client CLI: zoo tooling, k8s rendering, job arg plumbing."""

import os

from elasticdl_tpu.client.main import _split_args, _zoo_init, main


def test_zoo_init_scaffolds_project(tmp_path):
    path = str(tmp_path / "zoo")

    class A:
        pass

    args = A()
    args.path = path
    assert _zoo_init(args) == 0
    assert os.path.exists(os.path.join(path, "my_model.py"))
    assert os.path.exists(os.path.join(path, "Dockerfile"))
    # scaffolded model module must satisfy the zoo contract
    import importlib.util

    spec_mod = importlib.util.spec_from_file_location(
        "my_model", os.path.join(path, "my_model.py")
    )
    module = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(module)
    spec = module.model_spec()
    assert spec.name == "my_model"


def test_split_args_passthrough():
    cli, rest = _split_args([
        "--platform", "k8s", "--image", "img:1",
        "--model_zoo", "mnist", "--batch_size", "64",
    ])
    assert cli.platform == "k8s" and cli.image == "img:1"
    assert rest == ["--model_zoo", "mnist", "--batch_size", "64"]


def test_k8s_manifest_renders_master_pod():
    from elasticdl_tpu.client.k8s_submit import render_manifests

    manifest = render_manifests(
        ["--job_name", "myjob", "--model_zoo", "mnist"],
        image="img:2", namespace="ml",
    )
    assert '"name": "myjob-master"' in manifest
    assert '"namespace": "ml"' in manifest
    assert '"image": "img:2"' in manifest
    assert '"--model_zoo"' in manifest


def test_k8s_service_port_follows_job_port():
    """An explicit --port parameterizes the Service port/targetPort so
    worker pods dialing the service DNS name reach the master
    (ADVICE r3: it used to stay hard-coded at 50001)."""
    from elasticdl_tpu.client.k8s_submit import build_manifests

    _pod, svc = build_manifests(
        ["--job_name", "j", "--port", "6100"], image="i")
    assert svc["spec"]["ports"] == [{"port": 6100, "targetPort": 6100}]
    _pod, svc = build_manifests(["--job_name", "j"], image="i")
    assert svc["spec"]["ports"] == [
        {"port": 50001, "targetPort": 50001}]


def test_cli_help_and_unknown():
    assert main([]) == 1
