"""Fused GroupNorm kernel vs flax.linen.GroupNorm (interpret mode)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops import group_norm as gn


@pytest.fixture(autouse=True)
def interpret_mode(monkeypatch):
    monkeypatch.setenv("ELASTICDL_FUSED_GN", "interpret")


def _flax_gn(x, scale, bias, num_groups, relu):
    mod = nn.GroupNorm(num_groups=num_groups, epsilon=1e-6)
    y = mod.apply({"params": {"scale": scale, "bias": bias}}, x)
    return jax.nn.relu(y) if relu else y


@pytest.mark.parametrize("shape,groups", [
    ((2, 8, 8, 64), 32),
    ((3, 4, 4, 16), 8),
    ((2, 16, 32), 4),          # rank-3 input
])
@pytest.mark.parametrize("relu", [False, True])
def test_forward_matches_flax(shape, groups, relu):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    scale = jnp.asarray(rng.rand(shape[-1]) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(shape[-1]) * 0.1, jnp.float32)
    got = gn.fused_group_norm(x, scale, bias, groups, relu=relu)
    want = _flax_gn(x, scale, bias, groups, relu)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("relu", [False, True])
def test_gradients_match_flax(relu):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 4, 4, 32), jnp.float32)
    scale = jnp.asarray(rng.rand(32) + 0.5, jnp.float32)
    bias = jnp.asarray(rng.randn(32) * 0.1, jnp.float32)
    w = jnp.asarray(rng.randn(2, 4, 4, 32), jnp.float32)

    def loss_fused(x, s, b):
        return jnp.sum(gn.fused_group_norm(x, s, b, 8, relu=relu) * w)

    def loss_flax(x, s, b):
        return jnp.sum(_flax_gn(x, s, b, 8, relu) * w)

    g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(x, scale, bias)
    g2 = jax.grad(loss_flax, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(a, b_, atol=3e-5, rtol=3e-4)


def test_bf16_activations_path():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 4, 4, 32), jnp.bfloat16)
    scale = jnp.ones((32,), jnp.float32)
    bias = jnp.zeros((32,), jnp.float32)
    y = gn.fused_group_norm(x, scale, bias, 8, relu=True)
    assert y.dtype == jnp.bfloat16
    want = _flax_gn(x.astype(jnp.float32), scale, bias, 8, True)
    np.testing.assert_allclose(
        y.astype(np.float32), want, atol=3e-2, rtol=3e-2
    )
    # bwd runs in bf16 too
    g = jax.grad(
        lambda x: jnp.sum(
            gn.fused_group_norm(x, scale, bias, 8, relu=True)
            .astype(jnp.float32)
        )
    )(x)
    assert g.dtype == jnp.bfloat16


def test_large_mean_variance_stability():
    # E[x^2]-mean^2 catastrophically cancels for |mean| >> std; the
    # kernel must use the centered two-pass variance.  (flax's own
    # GroupNorm computes E[x^2]-mean^2 and is off by ~350 on this
    # input, so the oracle here is float64 numpy, not flax.)
    rng = np.random.RandomState(5)
    x64 = rng.randn(2, 8, 8, 32) * 0.1 + 3000.0
    x = jnp.asarray(x64, jnp.float32)
    scale = jnp.ones((32,), jnp.float32)
    bias = jnp.zeros((32,), jnp.float32)
    xr = x64.reshape(2, -1, 8, 4)
    m = xr.mean(axis=(1, 3), keepdims=True)
    v = ((xr - m) ** 2).mean(axis=(1, 3), keepdims=True)
    truth = ((xr - m) / np.sqrt(v + 1e-6)).reshape(x64.shape)
    got = gn.fused_group_norm(x, scale, bias, 8)
    np.testing.assert_allclose(got, truth, atol=1e-2, rtol=1e-2)


def test_off_mode_matches(monkeypatch):
    monkeypatch.setenv("ELASTICDL_FUSED_GN", "off")
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 4, 4, 32), jnp.float32)
    scale = jnp.ones((32,), jnp.float32)
    bias = jnp.zeros((32,), jnp.float32)
    got = gn.fused_group_norm(x, scale, bias, 8)
    want = _flax_gn(x, scale, bias, 8, False)
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_under_jit_and_grad_composes():
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2, 8, 8, 64), jnp.float32)
    scale = jnp.ones((64,), jnp.float32)
    bias = jnp.zeros((64,), jnp.float32)

    @jax.jit
    def step(x, s, b):
        return jax.value_and_grad(
            lambda x: jnp.sum(gn.fused_group_norm(x, s, b, 32,
                                                  relu=True) ** 2)
        )(x)

    v, g = step(x, scale, bias)
    assert np.isfinite(float(v))
    assert g.shape == x.shape
