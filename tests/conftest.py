"""Test env: force an 8-device virtual CPU platform.

Mirrors the driver's multi-chip dry-run environment so every sharding test
exercises a real (virtual) device mesh.  The session's sitecustomize
registers the axon TPU backend and sets jax_platforms via jax.config, so
overriding the env var alone is not enough — the config value must be
updated before any backend is initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

