"""Ulysses all-to-all sequence parallelism vs local attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.parallel.ring_attention import attention_local
from elasticdl_tpu.parallel.ulysses import ulysses_attention


def make_qkv(b=2, t=32, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    shape = (b, t, h, d)
    return tuple(
        jnp.asarray(rng.randn(*shape).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4])
def test_ulysses_matches_local(causal, sp):
    q, k, v = make_qkv()
    mesh = build_mesh(dp=2, tp=1, sp=sp,
                      devices=jax.devices()[: 2 * sp])
    ref = attention_local(q, k, v, causal=causal)
    out = ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_with_tp_sharded_heads():
    q, k, v = make_qkv(b=2, t=16, h=4, d=8)
    mesh = build_mesh(dp=2, tp=2, sp=2, devices=jax.devices())
    ref = attention_local(q, k, v, causal=True)
    out = ulysses_attention(q, k, v, mesh, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_inside_jit_grad():
    q, k, v = make_qkv(b=2, t=16, h=4, d=8)
    mesh = build_mesh(dp=1, tp=1, sp=4, devices=jax.devices()[:4])

    def loss(q, k, v):
        return ulysses_attention(q, k, v, mesh).sum()

    def loss_ref(q, k, v):
        return attention_local(q, k, v).sum()

    g = jax.jit(jax.grad(loss))(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_ulysses_head_divisibility_guard():
    q, k, v = make_qkv(b=2, t=16, h=2, d=8)   # 2 heads, sp=4
    mesh = build_mesh(dp=1, tp=1, sp=4, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="heads"):
        ulysses_attention(q, k, v, mesh)


def test_ulysses_no_sp_falls_back_to_local():
    q, k, v = make_qkv(b=2, t=16, h=2, d=8)
    out = ulysses_attention(q, k, v, None)
    ref = attention_local(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
