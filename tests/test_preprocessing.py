import numpy as np
import pytest

from elasticdl_tpu.preprocessing import analyzer_utils
from elasticdl_tpu.preprocessing.layers import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    LogRound,
    Normalizer,
    RaggedBatch,
    RoundIdentity,
    SparseEmbedding,
    ToNumber,
    ToRagged,
    ToSparse,
)


def test_log_round_reference_example():
    # reference docstring: base=2, [[1.2],[1.6],[0.2],[3.1],[100]]
    #   -> [[0],[1],[0],[2],[7]] (log_round.py:29-40)
    layer = LogRound(num_bins=16, base=2)
    out = layer(np.array([[1.2], [1.6], [0.2], [3.1], [100]]))
    np.testing.assert_array_equal(out, [[0], [1], [0], [2], [7]])


def test_round_identity_reference_example():
    layer = RoundIdentity(num_buckets=5)
    out = layer(np.array([[1.2], [1.6], [0.2], [3.1], [4.9]]))
    np.testing.assert_array_equal(out, [[1], [2], [0], [3], [4]])


def test_concatenate_with_offset_reference_example():
    a1 = np.array([[1], [1], [1]])
    a2 = np.array([[2], [2], [2]])
    layer = ConcatenateWithOffset(offsets=[0, 10], axis=1)
    np.testing.assert_array_equal(
        layer([a1, a2]), [[1, 12], [1, 12], [1, 12]]
    )


def test_discretization():
    layer = Discretization([0.0, 1.0, 10.0])
    np.testing.assert_array_equal(
        layer(np.array([-5.0, 0.5, 5.0, 50.0])), [0, 1, 2, 3]
    )


def test_hashing_deterministic_and_bounded():
    layer = Hashing(num_bins=7)
    ints = layer(np.arange(100))
    assert ((np.asarray(ints) >= 0) & (np.asarray(ints) < 7)).all()
    np.testing.assert_array_equal(layer(np.arange(100)), ints)
    strs = layer(np.array(["cat", "dog", "cat"], dtype=object))
    assert strs[0] == strs[2]
    assert 0 <= strs[1] < 7


def test_index_lookup_with_oov():
    layer = IndexLookup(["a", "b", "c"])
    np.testing.assert_array_equal(
        layer(np.array(["b", "zzz", "a"], dtype=object)), [1, 3, 0]
    )
    assert layer.vocab_size() == 4


def test_normalizer():
    layer = Normalizer(subtract=2.0, divide=4.0)
    np.testing.assert_allclose(layer(np.array([2.0, 6.0])), [0.0, 1.0])


def test_to_number_with_defaults():
    layer = ToNumber(out_type=np.float32, default_value=-1)
    out = layer(np.array(["1.5", "", b"2.5", "bad"], dtype=object))
    np.testing.assert_allclose(out, [1.5, -1.0, 2.5, -1.0])


def test_to_ragged_and_dense_mask():
    rb = ToRagged(sep=",")(["1,2,3", "4", ""])
    assert isinstance(rb, RaggedBatch)
    assert rb.row_lengths.tolist() == [3, 1, 0]
    ids = rb.map_values(lambda v: ToNumber(np.int64)(v))
    dense, mask = ids.to_dense(max_len=3)
    np.testing.assert_array_equal(dense, [[1, 2, 3], [4, 0, 0],
                                          [0, 0, 0]])
    np.testing.assert_array_equal(
        mask, [[1, 1, 1], [1, 0, 0], [0, 0, 0]]
    )


def test_to_sparse_shares_representation():
    rb = ToSparse()(["a,b", "c"])
    assert isinstance(rb, RaggedBatch)


def test_ragged_concatenate_with_offset():
    r1 = RaggedBatch.from_rows([[1, 2], [3]])
    r2 = RaggedBatch.from_rows([[5], [6, 7]])
    out = ConcatenateWithOffset(offsets=[0, 10])([r1, r2])
    assert [r.tolist() for r in out.rows()] == [[1, 2, 15], [3, 16, 17]]


@pytest.mark.parametrize("combiner,expect", [
    ("sum", [3.0, 0.0]),
    ("mean", [1.5, 0.0]),
    ("sqrtn", [3.0 / np.sqrt(2), 0.0]),
])
def test_sparse_embedding_combiners(combiner, expect):
    rows = np.array(
        [[[1.0], [2.0], [9.0]], [[9.0], [9.0], [9.0]]], np.float32
    )
    mask = np.array([[1, 1, 0], [0, 0, 0]], np.float32)
    out = SparseEmbedding(combiner)(rows, mask)
    np.testing.assert_allclose(np.asarray(out)[:, 0], expect, rtol=1e-6)


def test_analyzer_utils_roundtrip(monkeypatch):
    analyzer_utils.set_stats(
        "age", min=0, max=100, avg=35.5, stddev=10.0,
        count_distinct=77, bucket_boundaries=[10, 20, 30],
    )
    assert analyzer_utils.get_min("age") == 0
    assert analyzer_utils.get_max("age") == 100
    assert analyzer_utils.get_mean("age") == 35.5
    assert analyzer_utils.get_stddev("age") == 10.0
    assert analyzer_utils.get_distinct_count("age") == 77
    assert analyzer_utils.get_bucket_boundaries("age") == [10, 20, 30]
    assert analyzer_utils.get_min("unknown", default=5) == 5
