"""Loader robustness (serving/loader.py + the atomic export publish):
partial version dirs, staging leftovers, corrupt manifests, GC'd
pinned versions — the states a crashing writer or a retention pass can
leave behind, which the fleet scanner and the aggregation tier must
ride without ever serving a torn export."""

import json
import os

import numpy as np
import pytest

from elasticdl_tpu.serving.export import export_servable, publish_export
from elasticdl_tpu.serving.loader import (
    list_versions,
    load_servable,
    resolve_export_dir,
)

W = np.arange(8, dtype=np.float32).reshape(4, 2)


def _export(base, version):
    export_servable(
        os.path.join(str(base), str(version)),
        lambda p, x: x @ p["w"], {"w": W},
        np.zeros((1, 4), np.float32), model_name="lin",
        version=version, platforms=("cpu",),
    )


def test_atomic_publish_leaves_no_staging_dirs(tmp_path):
    _export(tmp_path, 1)
    assert sorted(os.listdir(tmp_path)) == ["1"]
    assert sorted(os.listdir(tmp_path / "1")) == [
        "manifest.json", "model.npz", "model.stablehlo"]


def test_publish_export_swaps_existing_dir_whole(tmp_path):
    target = tmp_path / "1"
    publish_export(str(target), {"manifest.json": b"{}",
                                 "old_leaf": b"x"})
    publish_export(str(target), {"manifest.json": b"{}",
                                 "new_leaf": b"y"})
    # The old dir's contents never mix into the new one.
    assert sorted(os.listdir(target)) == ["manifest.json", "new_leaf"]
    assert sorted(os.listdir(tmp_path)) == ["1"]


def test_partial_version_dir_is_skipped(tmp_path):
    _export(tmp_path, 1)
    _export(tmp_path, 3)
    # A torn pre-atomic export: leaf files, no manifest.
    os.makedirs(tmp_path / "5")
    (tmp_path / "5" / "model.npz").write_bytes(b"junk")
    assert list_versions(str(tmp_path)) == [1, 3]
    assert resolve_export_dir(str(tmp_path)).endswith("/3")


def test_tmp_leftovers_skipped_and_gc_reaps_them(tmp_path):
    _export(tmp_path, 2)
    os.makedirs(tmp_path / "4.tmp-12345")
    os.makedirs(tmp_path / "4.old-12345")
    os.makedirs(tmp_path / "7")  # manifest-less numeric dir
    # A plain reader never reaps another writer's staging dirs.
    assert list_versions(str(tmp_path)) == [2]
    assert (tmp_path / "4.tmp-12345").is_dir()
    # The base's OWNER reaps staging leftovers and torn numeric dirs;
    # complete versions stay, and so does the .old- sibling — after a
    # crash mid-swap it can be the only complete copy of that export.
    assert list_versions(str(tmp_path), gc_incomplete=True) == [2]
    assert sorted(os.listdir(tmp_path)) == ["2", "4.old-12345"]


def test_pinned_version_after_gc_fails_loudly(tmp_path):
    _export(tmp_path, 1)
    _export(tmp_path, 2)
    assert resolve_export_dir(str(tmp_path), version=1).endswith("/1")
    import shutil

    shutil.rmtree(tmp_path / "1")  # retention GC took it
    with pytest.raises(FileNotFoundError):
        resolve_export_dir(str(tmp_path), version=1)
    # The unpinned scan still resolves what remains.
    assert resolve_export_dir(str(tmp_path)).endswith("/2")


def test_corrupt_manifest_fails_at_load_not_silently(tmp_path):
    _export(tmp_path, 1)
    (tmp_path / "1" / "manifest.json").write_text("{not json")
    # Presence marks completeness (the atomic publisher can't write a
    # torn manifest)...
    assert list_versions(str(tmp_path)) == [1]
    # ...so corruption surfaces at LOAD, loudly, not as a skip.
    with pytest.raises(ValueError):
        load_servable(str(tmp_path / "1"))


def test_unknown_format_prefix_refused(tmp_path):
    _export(tmp_path, 1)
    manifest_path = tmp_path / "1" / "manifest.json"
    manifest = json.loads(manifest_path.read_text())
    manifest["format"] = "future-encoding+" + manifest["format"]
    manifest_path.write_text(json.dumps(manifest))
    with pytest.raises(ValueError, match="feature prefixes"):
        load_servable(str(tmp_path / "1"))


def test_direct_export_dir_still_resolves(tmp_path):
    _export(tmp_path, 1)
    direct = str(tmp_path / "1")
    assert resolve_export_dir(direct) == direct
    assert list_versions(direct) == []
