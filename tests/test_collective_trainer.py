"""Collective trainer on a virtual 8-device mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from elasticdl_tpu.models import mnist
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer


@pytest.fixture(scope="module")
def spec():
    return mnist.model_spec(learning_rate=1e-3)


def make_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), axis_names=("data",))


def test_single_device_step(spec):
    trainer = CollectiveTrainer(spec, batch_size=16)
    xs, ys = mnist.synthetic_data(n=16)
    loss1, v1 = trainer.train_minibatch(xs, ys)
    loss2, v2 = trainer.train_minibatch(xs, ys)
    assert v2 == v1 + 1
    assert np.isfinite(loss1) and np.isfinite(loss2)


def test_mesh_step_matches_single_device(spec):
    xs, ys = mnist.synthetic_data(n=64, seed=3)
    single = CollectiveTrainer(spec, batch_size=64, rng_seed=0)
    mesh = make_mesh(8)
    multi = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=0)
    # same global batch (64), same init seed -> same loss trajectory
    for _ in range(3):
        loss_s, _ = single.train_minibatch(xs, ys)
        loss_m, _ = multi.train_minibatch(xs, ys)
        np.testing.assert_allclose(loss_s, loss_m, rtol=2e-4)


def test_partial_batch_padding_no_recompile(spec):
    trainer = CollectiveTrainer(spec, batch_size=16)
    xs, ys = mnist.synthetic_data(n=40)
    trainer.train_minibatch(xs[:16], ys[:16])
    # partial batch: 8 records, padded to 16, masked in the loss
    loss, _ = trainer.train_minibatch(xs[32:40], ys[32:40])
    assert np.isfinite(loss)


def test_gradient_accumulation_matches_large_batch(spec):
    xs, ys = mnist.synthetic_data(n=64, seed=5)
    big = CollectiveTrainer(spec, batch_size=64, rng_seed=0)
    accum = CollectiveTrainer(spec, batch_size=16, accum_steps=4, rng_seed=0)
    loss_b, _ = big.train_minibatch(xs, ys)
    loss_a, _ = accum.train_minibatch(xs, ys)
    np.testing.assert_allclose(loss_b, loss_a, rtol=2e-4)


def test_elastic_mesh_rebuild(spec):
    """World resize: 8 -> 4 devices, training continues."""
    xs, ys = mnist.synthetic_data(n=32, seed=7)
    trainer = CollectiveTrainer(spec, batch_size=4, mesh=make_mesh(8))
    loss1, _ = trainer.train_minibatch(xs, ys)
    trainer.rebuild(make_mesh(4))  # lost half the world
    loss2, _ = trainer.train_minibatch(xs[:16], ys[:16])
    assert np.isfinite(loss1) and np.isfinite(loss2)
    assert trainer.global_device_count == 4


def test_fused_steps_match_sequential(spec):
    """K fused steps in one XLA program == K sequential step calls."""
    xs, ys = mnist.synthetic_data(n=16, seed=9)
    w = np.ones(16, np.float32)
    seq = CollectiveTrainer(spec, batch_size=16, rng_seed=2)
    fused_tr = CollectiveTrainer(spec, batch_size=16, rng_seed=2)
    for _ in range(3):
        seq.train_minibatch(xs, ys)
    fused = fused_tr.build_fused_steps(3)
    p, o, loss = fused(fused_tr._params, fused_tr._opt_state, xs, ys, w)
    p_seq = seq.export_parameters()
    import jax

    from elasticdl_tpu.utils.pytree import flatten_with_names, to_numpy

    p_fused, _ = flatten_with_names(to_numpy(p))
    for k in p_seq:
        np.testing.assert_allclose(p_seq[k], p_fused[k], rtol=2e-4,
                                   atol=1e-6)


def test_checkpoint_restore_roundtrip(spec, tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    xs, ys = mnist.synthetic_data(n=16)
    t1 = CollectiveTrainer(spec, batch_size=16, checkpoint_saver=saver,
                           checkpoint_steps=2)
    t1.train_minibatch(xs, ys)
    t1.train_minibatch(xs, ys)  # triggers checkpoint at version 2
    t1.flush_checkpoints()      # join the async write before restoring
    t2 = CollectiveTrainer(spec, batch_size=16, checkpoint_saver=saver)
    assert t2.init_from_checkpoint()
    assert t2.version == 2
    p1 = t1.export_parameters()
    p2 = t2.export_parameters()
    for k in p1:
        np.testing.assert_allclose(p1[k], p2[k], rtol=1e-6)


def test_restore_resumes_optimizer_trajectory(spec, tmp_path):
    """Kill-restore on the DP path reproduces the uninterrupted loss
    curve — Adam moments must survive the checkpoint (VERDICT r1: restore
    used optimizer.init, diverging from the uninterrupted trajectory)."""
    saver = CheckpointSaver(str(tmp_path))
    xs, ys = mnist.synthetic_data(n=16, seed=11)

    ref = CollectiveTrainer(spec, batch_size=16, rng_seed=4)
    losses_ref = [ref.train_minibatch(xs, ys)[0] for _ in range(4)]

    t1 = CollectiveTrainer(spec, batch_size=16, rng_seed=4,
                           checkpoint_saver=saver, checkpoint_steps=2)
    t1.train_minibatch(xs, ys)
    t1.train_minibatch(xs, ys)  # checkpoint at version 2 (with opt state)
    t1.flush_checkpoints()

    t2 = CollectiveTrainer(spec, batch_size=16, rng_seed=99,
                           checkpoint_saver=saver)
    assert t2.init_from_checkpoint() and t2.version == 2
    losses_resumed = [t2.train_minibatch(xs, ys)[0] for _ in range(2)]
    np.testing.assert_allclose(losses_resumed, losses_ref[2:], rtol=2e-4)


def test_restore_on_mesh_resumes_trajectory(spec, tmp_path):
    """Same, but the restored trainer comes back on an 8-device mesh —
    the elastic relaunch-onto-new-world path."""
    saver = CheckpointSaver(str(tmp_path))
    xs, ys = mnist.synthetic_data(n=32, seed=13)

    ref = CollectiveTrainer(spec, batch_size=32, rng_seed=6)
    losses_ref = [ref.train_minibatch(xs, ys)[0] for _ in range(4)]

    t1 = CollectiveTrainer(spec, batch_size=32, rng_seed=6,
                           checkpoint_saver=saver, checkpoint_steps=2)
    t1.train_minibatch(xs, ys)
    t1.train_minibatch(xs, ys)
    t1.flush_checkpoints()

    t2 = CollectiveTrainer(spec, batch_size=4, mesh=make_mesh(8),
                           rng_seed=99, checkpoint_saver=saver)
    assert t2.init_from_checkpoint()
    losses_resumed = [t2.train_minibatch(xs, ys)[0] for _ in range(2)]
    np.testing.assert_allclose(losses_resumed, losses_ref[2:], rtol=2e-4)


def test_zero1_matches_replicated_trajectory(spec):
    """ZeRO-1 optimizer-state sharding is semantically invisible: same
    loss trajectory as the replicated trainer, but Adam moments live
    sharded over the data axis."""
    xs, ys = mnist.synthetic_data(n=64, seed=17)
    mesh = make_mesh(8)
    base = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=3)
    z1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=3,
                           zero1=True)
    for _ in range(3):
        loss_b, _ = base.train_minibatch(xs, ys)
        loss_z, _ = z1.train_minibatch(xs, ys)
        np.testing.assert_allclose(loss_b, loss_z, rtol=2e-4)
    # at least one big optimizer leaf is actually sharded over dp
    from jax.sharding import PartitionSpec as P

    sharded = [
        leaf for leaf in jax.tree_util.tree_leaves(z1._opt_state)
        if hasattr(leaf, "sharding")
        and leaf.sharding.spec == P("data")
    ]
    assert sharded, "no optimizer leaf carries the dp sharding"


def test_zero1_checkpoint_restore_roundtrip(spec, tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    xs, ys = mnist.synthetic_data(n=32, seed=19)
    mesh = make_mesh(8)
    t1 = CollectiveTrainer(spec, batch_size=4, mesh=mesh, rng_seed=5,
                           zero1=True, checkpoint_saver=saver,
                           checkpoint_steps=2)
    ref = CollectiveTrainer(spec, batch_size=4, mesh=mesh, rng_seed=5)
    losses_ref = [ref.train_minibatch(xs, ys)[0] for _ in range(4)]
    t1.train_minibatch(xs, ys)
    t1.train_minibatch(xs, ys)
    t1.flush_checkpoints()
    t2 = CollectiveTrainer(spec, batch_size=4, mesh=mesh, rng_seed=9,
                           zero1=True, checkpoint_saver=saver)
    assert t2.init_from_checkpoint()
    resumed = [t2.train_minibatch(xs, ys)[0] for _ in range(2)]
    np.testing.assert_allclose(resumed, losses_ref[2:], rtol=2e-4)


def test_async_checkpoint_does_not_block_and_flushes(spec, tmp_path):
    """Checkpoint writes run off-thread; flush joins them and the files
    are valid afterwards."""
    saver = CheckpointSaver(str(tmp_path))
    xs, ys = mnist.synthetic_data(n=16, seed=23)
    t = CollectiveTrainer(spec, batch_size=16, checkpoint_saver=saver,
                          checkpoint_steps=1)
    for _ in range(3):
        t.train_minibatch(xs, ys)
    t.flush_checkpoints()
    assert saver.latest_version() == 3
    d, _, _ = saver.load()
    assert any(k.startswith("opt/") for k in d)
