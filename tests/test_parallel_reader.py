"""Multiprocess sharded reader + prefetcher (the odps_io equivalent,
reference data/odps_io.py:71-400)."""

import functools
import sqlite3
import time

import numpy as np
import pytest

from elasticdl_tpu.data.parallel_reader import (
    ParallelShardReader,
    _make_task,
    prefetch_batches,
)
from elasticdl_tpu.data.recio import RecioWriter
from elasticdl_tpu.data.sql_reader import SQLTableDataReader


def make_db(path, n=500):
    conn = sqlite3.connect(path)
    conn.execute("CREATE TABLE t (a REAL, b INTEGER)")
    conn.executemany(
        "INSERT INTO t VALUES (?, ?)",
        [(float(i), i % 7) for i in range(n)],
    )
    conn.commit()
    conn.close()


@pytest.mark.slow
def test_parallel_sql_reads_match_sequential(tmp_path):
    db = str(tmp_path / "t.db")
    make_db(db, n=500)
    factory = functools.partial(
        SQLTableDataReader, db, "t", records_per_shard=500
    )
    sequential = list(factory().read_records(_make_task("t", 0, 500)))
    with ParallelShardReader(
        factory, num_processes=3, records_per_subrange=64
    ) as reader:
        parallel = list(reader.read_records(_make_task("t", 0, 500)))
        assert parallel == sequential  # order preserved
        # shuffled record_indices honored too
        order = list(np.random.RandomState(0).permutation(100))
        shuffled = list(
            reader.read_records(_make_task("t", 0, 100, order))
        )
        assert shuffled == [sequential[i] for i in order]


@pytest.mark.slow
def test_parallel_recio_reads(tmp_path):
    from elasticdl_tpu.data.reader import RecioDataReader

    path = str(tmp_path / "data.recio")
    with RecioWriter(path) as w:
        for i in range(300):
            w.write(b"r%03d" % i)
    factory = functools.partial(RecioDataReader, str(tmp_path))
    with ParallelShardReader(
        factory, num_processes=2, records_per_subrange=50
    ) as reader:
        got = list(reader.read_records(_make_task(path, 0, 300)))
    assert got == [b"r%03d" % i for i in range(300)]


def test_prefetch_overlaps_and_preserves_order():
    produced = []

    def slow_batches():
        for i in range(5):
            time.sleep(0.02)
            produced.append(i)
            yield i

    got = []
    for batch in prefetch_batches(slow_batches(), depth=2):
        time.sleep(0.02)  # "device step"
        got.append(batch)
    assert got == [0, 1, 2, 3, 4]


def test_prefetch_reraises_producer_error():
    def bad_batches():
        yield 1
        raise RuntimeError("disk on fire")

    it = prefetch_batches(bad_batches(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="disk on fire"):
        list(it)


def test_prefetch_abandoned_consumer_unblocks_producer():
    """Breaking out of the consumer must not leave the producer thread
    pinned on a full queue (review r2 finding)."""
    import threading

    state = {"closed": False}

    def batches():
        try:
            for i in range(1000):
                yield i
        finally:
            state["closed"] = True

    gen = prefetch_batches(batches(), depth=1)
    assert next(gen) == 0
    gen.close()  # consumer walks away
    deadline = time.time() + 5
    while time.time() < deadline and not state["closed"]:
        time.sleep(0.05)
    assert state["closed"], "producer never released the batch iterator"
    assert threading.active_count() < 50
