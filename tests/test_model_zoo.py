"""Model-zoo smoke + convergence tests across the families."""

import os

import numpy as np
import pytest

from elasticdl_tpu.models import (
    census_dnn,
    census_sqlflow,
    dcn,
    iris,
    mobilenet,
    wide_deep,
    xdeepfm,
)
from elasticdl_tpu.models.spec import load_model_spec
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer
from elasticdl_tpu.worker.ps_trainer import ParameterServerTrainer
from tests.test_pserver import start_ps, stop_all


def test_load_model_spec_by_short_name():
    spec = load_model_spec("mnist")
    assert spec.name == "mnist"
    spec = load_model_spec("elasticdl_tpu.models.iris")
    assert spec.name == "iris"


def test_mobilenetv2_param_count_near_reference():
    """Reference MobileNetV2 has 2,236,682 params
    (ftlib_benchmark.md:45); ours should land in the same ballpark
    (GroupNorm vs BatchNorm shifts the count slightly)."""
    import jax

    spec = mobilenet.model_spec()
    params = spec.init_fn(jax.random.PRNGKey(0))
    count = sum(np.prod(p.shape) for p in
                jax.tree_util.tree_leaves(params))
    assert 1.8e6 < count < 2.8e6, count


def test_resnet_s2d_stem():
    """Space-to-depth stem (MXU-shaped first conv, VERDICT r3 #5):
    the transform is an exact invertible reshuffle, the s2d model's
    feature maps keep the standard resnet50 shapes from the pool down
    (so every later layer is identical), and a step trains."""
    import jax
    import jax.numpy as jnp

    from elasticdl_tpu.models import resnet

    x = np.arange(2 * 8 * 8 * 3, dtype=np.float32).reshape(2, 8, 8, 3)
    s = resnet.space_to_depth(jnp.asarray(x), 2)
    assert s.shape == (2, 4, 4, 12)
    # block (i,j) of the input is channel-sliced intact: position
    # [b, h, w, (di*2+dj)*3 + c] == input [b, 2h+di, 2w+dj, c]
    np.testing.assert_array_equal(
        np.asarray(s)[0, 1, 2, :3], x[0, 2, 4, :3])
    np.testing.assert_array_equal(
        np.asarray(s)[0, 1, 2, 9:], x[0, 3, 5, :3])

    spec = resnet.model_spec(variant="resnet50_s2d", num_classes=10,
                             image_size=64, learning_rate=0.1)
    params = spec.init_fn(jax.random.PRNGKey(0))
    stem = params["Conv_0"]["kernel"]
    assert stem.shape == (4, 4, 12, 64)  # vs (7, 7, 3, 64) baseline
    logits = spec.apply_fn(params, np.zeros((2, 64, 64, 3), np.float32),
                           True)
    assert logits.shape == (2, 10)
    trainer = CollectiveTrainer(spec, batch_size=4)
    xs = np.random.RandomState(0).rand(4, 64, 64, 3).astype(np.float32)
    ys = np.arange(4, dtype=np.int32) % 10
    loss, _ = trainer.train_minibatch(xs, ys)
    assert np.isfinite(loss)


def test_mobilenetv2_trains():
    spec = mobilenet.model_spec(learning_rate=0.01)
    trainer = CollectiveTrainer(spec, batch_size=8)
    rng = np.random.RandomState(0)
    xs = rng.rand(8, 32, 32, 3).astype(np.float32)
    ys = rng.randint(0, 10, 8).astype(np.int32)
    loss, _ = trainer.train_minibatch(xs, ys)
    assert np.isfinite(loss)


def test_iris_learns_from_csv(tmp_path):
    path = iris.synthetic_iris_csv(str(tmp_path / "iris.csv"), n=120)
    with open(path) as f:
        records = [line.strip().split(",") for line in f]
    spec = iris.model_spec(learning_rate=0.05)
    trainer = CollectiveTrainer(spec, batch_size=32)
    for _ in range(12):
        for i in range(0, 120, 32):
            xs, ys = spec.feed(records[i:i + 32])
            trainer.train_minibatch(xs, ys)
    xs, ys = spec.feed(records)
    correct = 0
    for i in range(0, 120, 32):
        out, labels = trainer.evaluate_minibatch(xs[i:i + 32],
                                                 ys[i:i + 32])
        correct += (np.argmax(out, -1) == labels).sum()
    assert correct / 120 > 0.8


@pytest.mark.parametrize("module", [dcn, xdeepfm])
def test_ctr_models_train_through_ps(module):
    spec = module.model_spec(vocab_size=500, embedding_dim=4,
                             hidden=(16,))
    client, servicers, servers = start_ps(
        num_ps=1, opt_type="adam", opt_args="learning_rate=0.01",
    )
    try:
        trainer = ParameterServerTrainer(spec, client, batch_size=32)
        dense, ids, labels = module.synthetic_data(n=64, vocab_size=500)
        records = [(dense[i], ids[i], labels[i]) for i in range(64)]
        feats, ys = spec.feed(records[:32])
        loss1, _ = trainer.train_minibatch(feats, ys)
        for _ in range(10):
            loss2, _ = trainer.train_minibatch(feats, ys)
        assert np.isfinite(loss2) and loss2 < loss1
    finally:
        stop_all(servers)


def test_wide_deep_census_through_ps():
    spec = wide_deep.model_spec(embedding_dim=4, hidden=(16,))
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="adam", opt_args="learning_rate=0.01",
    )
    try:
        trainer = ParameterServerTrainer(spec, client, batch_size=32)
        rows = wide_deep.synthetic_census_rows(n=256)
        losses = []
        for epoch in range(4):
            for i in range(0, 256, 32):
                feats, ys = spec.feed(rows[i:i + 32])
                loss, _ = trainer.train_minibatch(feats, ys)
                losses.append(loss)
        assert losses[-1] < losses[0]
    finally:
        stop_all(servers)


@pytest.mark.parametrize("make_spec", [
    lambda: census_dnn.model_spec(embedding_dim=4, hidden=(16,)),
    lambda: census_sqlflow.model_spec("wide_and_deep",
                                      embedding_dim=4, hidden=(16,)),
    lambda: census_sqlflow.model_spec("dnn", embedding_dim=4,
                                      hidden=(16,)),
], ids=["census_dnn", "sqlflow_wide_deep", "sqlflow_dnn"])
def test_census_models_train_through_ps(make_spec):
    spec = make_spec()
    client, servicers, servers = start_ps(
        num_ps=2, opt_type="adam", opt_args="learning_rate=0.01",
    )
    try:
        trainer = ParameterServerTrainer(spec, client, batch_size=32)
        records = census_dnn.synthetic_census_records(n=256)
        losses = []
        for epoch in range(4):
            for i in range(0, 256, 32):
                feats, ys = spec.feed(records[i:i + 32])
                loss, _ = trainer.train_minibatch(feats, ys)
                losses.append(loss)
        assert np.isfinite(losses[-1]) and losses[-1] < losses[0]
    finally:
        stop_all(servers)


def test_census_sqlflow_clause_compiles_to_disjoint_id_spaces():
    groups = census_sqlflow.build_groups()
    # Groups mirror the .sql's three CONCAT clauses.
    assert sorted(groups) == ["group_1", "group_2", "group_3"]
    records = census_dnn.synthetic_census_records(n=64)
    columns = {k: [r[k] for r in records] for k in records[0]}
    for concat in groups.values():
        ids = concat.transform(columns)
        assert ids.shape == (64, len(concat.columns))
        assert ids.min() >= 0 and ids.max() < concat.num_buckets
        # Per-field slices live in disjoint offset ranges.
        for j, (col, off) in enumerate(
            zip(concat.columns, concat.offsets)
        ):
            assert ids[:, j].min() >= off
            assert ids[:, j].max() < off + col.num_buckets


def test_census_dnn_stats_standardization(monkeypatch):
    # Analyzer-exported stats flow into the numeric columns
    # (use_stats=True), the reference's _ELASTICDL_* env scheme.
    from elasticdl_tpu.preprocessing import analyzer_utils

    monkeypatch.setenv("_EDL_TPU_AGE_AVG", "40")
    monkeypatch.setenv("_EDL_TPU_AGE_STDDEV", "10")
    assert analyzer_utils.get_mean("age") == 40.0
    numeric, _ = census_dnn.build_columns(use_stats=True)
    age = [c for c in numeric if c.key == "age"][0]
    out = age.transform(["50", "30"])
    assert np.allclose(out, [1.0, -1.0])


@pytest.mark.slow
def test_transformer_lm_managed_job_e2e(tmp_path):
    """The flagship LM trains through the FULL managed path: master,
    dynamic shards over the synthetic-LM origin, worker subprocess,
    model_params plumbing — and the loss on the structured sequences
    drops."""
    import subprocess
    import sys

    log = tmp_path / "job.log"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTICDL_TPU_PLATFORM"] = "cpu"
    with open(log, "w") as f:
        proc = subprocess.run(
            [sys.executable, "-m", "elasticdl_tpu.master.main",
             "--data_origin", "synthetic_lm:512:64:512",
             "--model_zoo", "transformer",
             "--model_params",
             "vocab_size=512;dim=64;num_heads=4;num_layers=2;seq_len=64",
             "--batch_size", "16", "--num_epochs", "2",
             "--num_workers", "1", "--num_minibatches_per_task", "4",
             "--log_loss_steps", "8"],
            stdout=f, stderr=subprocess.STDOUT, env=env, timeout=420,
        )
    text = log.read_text()
    assert proc.returncode == 0, text[-2000:]
    assert "job finished" in text
    import re

    losses = [float(m) for m in re.findall(r"loss[=: ]+([0-9.]+)", text)]
    assert len(losses) >= 2, text[-2000:]
    assert losses[-1] < losses[0], losses
