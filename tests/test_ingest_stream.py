"""Cross-host streamed ingest (PR 17): the aggregator's POST /ingest
endpoint + the frame client SDK + the exporter's ``stream_to`` hook.

This is the real three-host topology under test: trainer and
aggregator share NO filesystem — versions arrive only as
``model.frame`` blobs over HTTP.  The status contract is load-bearing
(the exporter's recovery differs per cause): 400 malformed, 409
stale, 415 wrong content type, 422 program missing; and the endpoint
must survive every rejection on a keep-alive connection."""

import numpy as np
import pytest

from elasticdl_tpu.aggregation import ModelAggregator
from elasticdl_tpu.aggregation.main import IngestServer
from elasticdl_tpu.client.frame_client import (
    FrameClient,
    FrameClientError,
    ProgramRequiredError,
    StaleVersionError,
)
from elasticdl_tpu.serving.export import ContinuousExporter
from elasticdl_tpu.serving.loader import load_servable
from elasticdl_tpu.utils import tensor_codec
from elasticdl_tpu.utils.tensor_codec import FrameError


def _apply(p, x):
    return x @ p["w"]


def _exporter(base):
    return ContinuousExporter(str(base), model_name="lin",
                              platforms=("cpu",))


def _frame(ce, version, value, **kw):
    return ce.frame_bytes(
        version, _apply,
        {"w": np.full((4, 2), value, np.float32)},
        np.zeros((1, 4), np.float32), **kw)


@pytest.fixture
def rig(tmp_path):
    # Disjoint directories: the aggregator's scan source is never
    # written; everything arrives over the wire.
    agg = ModelAggregator(str(tmp_path / "agg_src"),
                          str(tmp_path / "pub"),
                          window=2, mode="latest")
    server = IngestServer(agg, port=0, host="127.0.0.1")
    server.start()
    client = FrameClient("127.0.0.1:%d" % server.port, timeout=30)
    ce = _exporter(tmp_path / "trainer_side")
    try:
        yield agg, server, client, ce, tmp_path / "pub"
    finally:
        client.close()
        server.stop()


def test_ingest_roundtrip_and_status_contract(rig):
    agg, server, client, ce, pub = rig
    assert client.ingest(_frame(ce, 1, 1.0)) == 1
    assert client.ingest(_frame(ce, 2, 2.0)) == 2
    # 409: stale version, surfaced as the typed skip signal
    with pytest.raises(StaleVersionError) as err:
        client.ingest(_frame(ce, 1, 9.0))
    assert err.value.status == 409
    # 400: a malformed blob is the SAME exception a local decode
    # raises
    with pytest.raises(FrameError):
        client.ingest(b"\xff" * 64)
    # 415: wrong content type (this endpoint speaks only frames)
    status, _, _ = client.roundtrip("/ingest", b"{}",
                                    content_type="application/json")
    assert status == 415
    # 404: unknown path
    status, _, _ = client.roundtrip("/nope", b"")
    assert status == 404
    # the aggregator state is what the wire said
    version, _ = agg.publish()
    assert version == 2
    model = load_servable(str(pub / "2"))
    out = np.asarray(model.predict(np.ones((1, 4), np.float32)))
    assert out[0, 0] == pytest.approx(8.0)
    counters = agg.stats()["counters"]
    assert counters["ingested_frames"] == 2
    assert counters["stale_exports_skipped"] == 1
    assert counters["ingest_frame_rejected"] == 1


def test_hostile_blobs_then_keep_alive_survives(rig):
    _, _, client, ce, _ = rig
    good = _frame(ce, 1, 1.0)
    hostiles = [
        good[: len(good) - 7],                     # truncated
        b"NOPE" + good[4:],                        # foreign magic
        good[:4] + (2 ** 31).to_bytes(4, "little") + good[8:],
        tensor_codec.encode_frame(                 # wrong kind
            {"x": np.zeros(1, np.float32)}, kind="predict"),
    ]
    for blob in hostiles:
        with pytest.raises(FrameError):
            client.ingest(blob)
    # same client, pooled connections: a good push still lands
    assert client.ingest(good) == 1


def test_422_when_aggregator_lost_its_program_cache(rig):
    agg, server, client, ce, _ = rig
    assert client.ingest(_frame(ce, 1, 1.0)) == 1
    # weights-only frame for a NEW tree: this aggregator has never
    # seen its program
    blob = ce.frame_bytes(
        2, lambda p, x: x @ p["w2"],
        {"w2": np.full((4, 3), 1.0, np.float32)},
        np.zeros((1, 4), np.float32), include_program=False)
    with pytest.raises(ProgramRequiredError) as err:
        client.ingest(blob)
    assert err.value.status == 422
    assert agg.stats()["counters"]["program_missing_rejected"] == 1
    # nothing was partially applied: the window still publishes v1
    assert agg.publish()[0] == 1


def test_stream_to_re_primes_after_aggregator_restart(rig, tmp_path):
    agg, server, client, ce, pub = rig
    params = {"w": np.full((4, 2), 1.0, np.float32)}
    x = np.zeros((1, 4), np.float32)
    assert ce.stream_to(client, 1, _apply, params, x) == 1
    assert ce.stream_to(client, 2, _apply, params, x) == 2
    # stale re-send: swallowed as a skip, not an error
    assert ce.stream_to(client, 1, _apply, params, x) is None
    assert ce.stream_stats == {"pushed": 2, "stale": 1, "reprimed": 0}
    # Mid-stream aggregator restart: a FRESH aggregator (empty program
    # cache) behind a new endpoint.  The exporter's steady-state
    # weights-only push must trigger the 422 -> include_program=True
    # re-prime WITHOUT trainer intervention.
    server.stop()
    agg2 = ModelAggregator(str(tmp_path / "agg2_src"),
                           str(tmp_path / "pub2"),
                           window=2, mode="latest")
    server2 = IngestServer(agg2, port=0, host="127.0.0.1")
    server2.start()
    client2 = FrameClient("127.0.0.1:%d" % server2.port)
    try:
        assert ce.stream_to(client2, 3, _apply, params, x) == 3
        assert ce.stream_stats["reprimed"] == 1
        assert ce.stream_stats["pushed"] == 3
        version, _ = agg2.publish()
        assert version == 3
        model = load_servable(str(tmp_path / "pub2" / "3"))
        out = np.asarray(model.predict(np.ones((1, 4), np.float32)))
        assert out.shape == (1, 2)
    finally:
        client2.close()
        server2.stop()


def test_cross_host_drill_freshness_slo_green(rig):
    """The acceptance drill: trainer and aggregator in disjoint
    directories, versions arriving ONLY through the streamed endpoint,
    and the freshness SLO (publish wall - export birth) green."""
    agg, server, client, ce, pub = rig
    params = {"w": np.full((4, 2), 3.0, np.float32)}
    x = np.zeros((1, 4), np.float32)
    for v in (1, 2):
        assert ce.stream_to(client, v, _apply, params, x) == v
    version, _ = agg.publish()
    assert version == 2
    stats = agg.stats()
    assert stats["freshness_seconds"] is not None
    assert stats["freshness_seconds"] < stats["freshness_slo_secs"]
    # the aggregator's scan source stayed empty the whole time: no
    # filesystem was shared
    assert agg.stats()["counters"].get("ingested", 0) == 2
    assert stats["counters"]["ingested_frames"] == 2


def test_error_mapping_unknown_status():
    err = FrameClient._error(503, b'{"error": "draining"}')
    assert isinstance(err, FrameClientError)
    assert err.status == 503 and "draining" in err.message
    assert isinstance(FrameClient._error(400, b'{"error": "x"}'),
                      FrameError)
    assert isinstance(FrameClient._error(409, b"{}"),
                      StaleVersionError)
    assert isinstance(FrameClient._error(422, b"not json"),
                      ProgramRequiredError)
