"""PS crash-restart recovery (docs/ps_recovery.md): restart-generation
fencing, coordinated cross-shard checkpoints, worker outage-riding and
rollback reconciliation — the unit half of bench_elastic's cpu_ps_kill
drill."""

import os
import threading

import grpc
import numpy as np
import pytest

from elasticdl_tpu.models import deepfm
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.proto import rpc
from elasticdl_tpu.ps.server import establish_generation
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.utils.retry import RetryPolicy, ps_rpc_policy
from elasticdl_tpu.worker.ps_client import PSClient, build_ps_client
from elasticdl_tpu.worker.ps_trainer import (
    GradientsRejected,
    ParameterServerTrainer,
)
from tests.test_pserver import start_ps, stop_all

VOCAB = 200


def make_spec():
    return deepfm.model_spec(vocab_size=VOCAB, embedding_dim=4,
                             hidden=(8,))


def make_batches(spec, n=128, batch_size=32):
    dense, ids, labels = deepfm.synthetic_data(n=n, vocab_size=VOCAB,
                                               seed=7)
    out = []
    for i in range(0, n, batch_size):
        records = [(dense[j], ids[j], labels[j])
                   for j in range(i, i + batch_size)]
        out.append(spec.feed(records))
    return out


def simulate_restart(servicer, generation, rollback_to=None):
    """In-process stand-in for SIGKILL + relaunch-with-restore on the
    same port: the serving incarnation's generation bumps and (with
    ``rollback_to``) the params version rolls back to the restored
    checkpoint label."""
    servicer.generation = generation
    servicer._staged.clear()   # staged 2PC txns died with the process
    if rollback_to is not None:
        servicer._params.version = rollback_to


# -- restart-generation establishment -----------------------------------


def test_generation_monotone_across_restarts(tmp_path):
    d = str(tmp_path)
    assert establish_generation(d, 0) == 1
    assert establish_generation(d, 0) == 2
    assert establish_generation(d, 0) == 3
    # Sibling shards count independently.
    assert establish_generation(d, 1) == 1


def test_generation_hint_moves_forward_only(tmp_path):
    d = str(tmp_path)
    # Persisted counter lost (fresh dir) but the launcher knows this is
    # launch #4: the hint wins.
    assert establish_generation(d, 0, hint=4) == 4
    # Persisted 4 now beats a stale/lower hint.
    assert establish_generation(d, 0, hint=2) == 5


def test_generation_without_dir_is_constant():
    # Nothing to persist against and no hint: constant 1 (fencing needs
    # a persisted counter or a counting launcher).
    assert establish_generation("", 0) == 1
    assert establish_generation("", 0) == 1


# -- servicer fencing ----------------------------------------------------


def test_push_from_dead_incarnation_rejected_not_applied():
    client, servicers, servers = start_ps(num_ps=1, generation=1)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        accepted, _ = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=0
        )
        assert accepted
        _, _, before = client.pull_dense_parameters(-1)

        # The shard restarts under the client (rolled back to v0); the
        # client still stamps generation 1.
        simulate_restart(servicers[0], generation=2, rollback_to=0)
        assert client.known_generation(0) == 1
        accepted, _ = client.push_gradients(
            {"w": np.full(4, 100.0, np.float32)}, version=1
        )
        assert not accepted
        assert servicers[0].counters["push_gen_rejected"] == 1
        # NOT applied — in async mode the version check alone would
        # have taken this as a "future-version" gradient.
        np.testing.assert_array_equal(
            servicers[0]._params.get_dense()["w"], before["w"]
        )
        # The reject response carried the new generation: the client
        # noted it and bumped its reconcile epoch.
        assert client.known_generation(0) == 2
        assert client.generation_epoch == 1
    finally:
        stop_all(servers)


def test_frozen_generation_snapshot_fences_deferred_push():
    """A deferred (pipelined) push is stamped with the generation its
    gradients were computed under — the caller's frozen snapshot — not
    whatever the client learned by the time it executes.  Otherwise an
    earlier push's fenced reject would teach the client the new
    generation and let the NEXT queued dead-incarnation gradient ride
    in under it."""
    client, servicers, servers = start_ps(num_ps=1, generation=1)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        client.pull_dense_parameters(-1)
        frozen = client.generation_snapshot()
        assert frozen == [1]

        simulate_restart(servicers[0], generation=2, rollback_to=0)
        # The client learns the restart (e.g. an earlier queued push
        # was fenced)...
        client.pull_dense_parameters(-1)
        assert client.known_generation(0) == 2
        # ...but the deferred push still carries the FROZEN stamp and
        # must be fenced.
        accepted, _ = client.push_gradients(
            {"w": np.full(4, 7.0, np.float32)}, version=1,
            generations=frozen,
        )
        assert not accepted
        assert servicers[0].counters["push_gen_rejected"] == 1
    finally:
        stop_all(servers)


def test_unstamped_legacy_push_still_accepted():
    client, servicers, servers = start_ps(num_ps=1, generation=3)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        # Hand-built legacy request: generation unset (0).
        from elasticdl_tpu.utils import tensor_codec

        model = tensor_codec.model_to_pb(
            dense={"w": np.full(4, 0.5, np.float32)}, version=0
        )
        res = servicers[0].push_gradients(
            pb.PushGradientsRequest(gradients=model)
        )
        assert res.accepted and res.generation == 3
    finally:
        stop_all(servers)


def test_pull_with_stale_generation_bypasses_fast_path():
    client, servicers, servers = start_ps(num_ps=1, generation=1)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        for v in range(4):
            client.push_gradients(
                {"w": np.full(4, 0.5, np.float32)}, version=v
            )
        # Client is at v4; the shard restarts restored at v2 — the
        # server's version is BELOW the client's, so the plain fast
        # path would return nothing forever.
        simulate_restart(servicers[0], generation=2, rollback_to=2)
        initialized, version, dense = client.pull_dense_parameters(4)
        assert initialized and version == 2
        assert "w" in dense, (
            "rolled-back shard starved the stale-generation client "
            "through the version fast path"
        )
        # Same request from a client already AT the new generation
        # takes the fast path again (no redundant payload).
        _, _, dense2 = client.pull_dense_parameters(4)
        assert dense2 == {}
    finally:
        stop_all(servers)


def test_mixed_generation_prepare_aborts_2pc_on_every_shard():
    """Sync-mode 2PC across a mid-transaction shard restart: the
    restarted shard fences its prepare, so the coordinator aborts the
    commit on EVERY shard — versions advance nowhere."""
    client, servicers, servers = start_ps(
        num_ps=2, use_async=False, grads_to_wait=1, generation=1,
    )
    try:
        client.push_model(
            {"a": np.ones(4, np.float32), "b": np.ones(4, np.float32)}
        )
        accepted, _ = client.push_gradients_atomic(
            {"a": np.full(4, 0.5, np.float32),
             "b": np.full(4, 0.5, np.float32)}, version=0,
        )
        assert accepted
        versions = [s._params.version for s in servicers]

        simulate_restart(servicers[0], generation=2)
        accepted, _ = client.push_gradients_atomic(
            {"a": np.full(4, 9.0, np.float32),
             "b": np.full(4, 9.0, np.float32)}, version=1,
        )
        assert not accepted
        assert [s._params.version for s in servicers] == versions, (
            "an aborted 2PC half-applied on a surviving shard"
        )
        assert servicers[0].counters["push_gen_rejected"] == 1
        assert client.generation_epoch == 1
    finally:
        stop_all(servers)


# -- worker outage riding ------------------------------------------------


def test_client_rides_shard_relaunch_on_same_port():
    """Kill the in-process server and boot a fresh one on the SAME
    port mid-retry: the armed client rebuilds its channel and the pull
    lands on the new incarnation without the caller seeing an error."""
    from elasticdl_tpu.ps.optimizer import create_optimizer
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer

    def boot(port, generation):
        params = Parameters()
        servicer = PserverServicer(
            params, create_optimizer("sgd", "learning_rate=0.1"),
            ps_id=0, num_ps=1, generation=generation,
        )
        server = grpc_utils.build_server(max_workers=8)
        rpc.add_pserver_servicer(servicer, server)
        port = server.add_insecure_port("[::]:%d" % port)
        server.start()
        return servicer, server, port

    servicer, server, port = boot(0, generation=1)
    addr = "localhost:%d" % port
    client = build_ps_client(
        [addr], retry=ps_rpc_policy(deadline_secs=30.0)
    )
    client.push_model({"w": np.ones(4, np.float32)})
    client.pull_dense_parameters(-1)   # learn the serving generation
    assert client.known_generation(0) == 1

    server.stop(grace=None)
    relaunched = {}

    def relaunch_later():
        servicer2, server2, _ = boot(port, generation=2)
        relaunched["servicer"] = servicer2
        relaunched["server"] = server2

    timer = threading.Timer(1.0, relaunch_later)
    timer.start()
    try:
        # Rides the dead window (~1s), lands on generation 2, which is
        # uninitialized — exactly what the trainer's push-to-init path
        # consumes.
        initialized, _, _ = client.pull_dense_parameters(-1)
        assert not initialized
        assert client.known_generation(0) == 2
        assert client.generation_epoch == 1
    finally:
        timer.join()
        if "server" in relaunched:
            relaunched["server"].stop(grace=None)


def test_fail_fast_without_policy():
    """No retry policy (legacy construction): a dead shard surfaces
    immediately as RpcError — the worker-level minibatch retry is then
    the only ride-out, as before this PR."""
    client, servicers, servers = start_ps(num_ps=1)
    client.push_model({"w": np.ones(4, np.float32)})
    stop_all(servers)
    with pytest.raises(grpc.RpcError):
        client.pull_dense_parameters(-1)


# -- trainer rollback reconciliation ------------------------------------


def test_trainer_reconciles_rollback_past_fast_path():
    """Shard restarts restored at an OLDER version, detected by the
    fenced push (get_model_steps > 1, so no cadence pull intervenes):
    the trainer re-pulls the FULL dense state — bypassing its
    local-version fast path, which a rolled-back server would starve —
    and resumes from the restored params."""
    spec = make_spec()
    client, servicers, servers = start_ps(num_ps=1, generation=1)
    try:
        trainer = ParameterServerTrainer(spec, client, batch_size=32,
                                         get_model_steps=4)
        data = make_batches(spec)
        for features, labels in data[:3]:
            trainer.train_minibatch(features, labels)
        assert servicers[0]._params.version == 3

        # Restart restored at v1 — and zero the server's actual dense
        # payload in place so the forced re-pull is observable (a
        # fast-path pull would return version 1 with NO data, leaving
        # the local params silently stale).
        with servicers[0]._lock:
            for arr in servicers[0]._params.get_dense().values():
                arr[...] = 0.0
        simulate_restart(servicers[0], generation=2, rollback_to=1)

        with pytest.raises(GradientsRejected):
            trainer.train_minibatch(*data[3])
        # The reconcile already ran inside the reject path: version AND
        # payload adopted from the restored shard, past the fast path.
        assert trainer.version == 1
        for name, arr in trainer.export_parameters().items():
            np.testing.assert_array_equal(
                arr, np.zeros_like(arr),
                err_msg="%s kept the dead incarnation's value" % name,
            )
        assert trainer._seen_gen_epoch == client.generation_epoch == 1
        # The worker's normal retry loop then succeeds.
        loss, _ = trainer.train_minibatch(*data[3])
        assert np.isfinite(loss)
    finally:
        stop_all(servers)


def test_cadence_pull_rides_restart_without_a_reject():
    """At get_model_steps=1 the cadence pull reaches the restarted
    shard FIRST, still stamped with the old generation: the server's
    stale-generation bypass hands back the full restored state, the
    push that follows is stamped with the new generation, and training
    rides the restart without even a GradientsRejected."""
    spec = make_spec()
    client, servicers, servers = start_ps(num_ps=1, generation=1)
    try:
        trainer = ParameterServerTrainer(spec, client, batch_size=32)
        data = make_batches(spec)
        for features, labels in data[:3]:
            trainer.train_minibatch(features, labels)

        with servicers[0]._lock:
            for arr in servicers[0]._params.get_dense().values():
                arr[...] = 0.0
        simulate_restart(servicers[0], generation=2, rollback_to=1)

        loss, _ = trainer.train_minibatch(*data[3])
        assert np.isfinite(loss)
        assert trainer.version == 1
        assert client.known_generation(0) == 2
        assert servicers[0].counters["push_gen_rejected"] == 0
    finally:
        stop_all(servers)


def test_pipelined_pushes_dropped_not_misapplied():
    """async_push_window > 0: pushes queued behind the compute when the
    shard dies are stamped by the dead incarnation — on reconcile they
    are waited out and DROPPED (the shard fences each), never surfaced
    as staleness rejects nor re-pushed against restored state."""
    spec = make_spec()
    client, servicers, servers = start_ps(num_ps=1, generation=1)
    try:
        trainer = ParameterServerTrainer(
            spec, client, batch_size=32, get_model_steps=4,
            async_push_window=2,
        )
        data = make_batches(spec)
        trainer.train_minibatch(*data[0])
        trainer.drain_pushes()
        v_applied = servicers[0]._params.version
        accepted_before = servicers[0].counters["push_accepted"]

        simulate_restart(servicers[0], generation=2,
                         rollback_to=v_applied)
        # Seed a prefetched entry BEFORE the client can learn about the
        # restart, to prove the reconcile invalidates it.  (Embedding
        # pulls now carry the generation stamp too — the serving-tier
        # lookup plane — so the very first post-restart minibatch's
        # pull teaches the client, not only the fenced push responses.)
        trainer._prefetched[("emb", b"sentinel")] = None
        # These steps pipeline pushes stamped with the generation the
        # local params were last SYNCED under (gen 1 — unless an
        # embedding pull's stamp or the executor's fenced reject lands
        # between them, in which case a later step reconciles first and
        # its push legitimately carries gen 2; all interleavings are
        # valid, the invariant below is interleaving-free).
        trainer.train_minibatch(*data[1])
        trainer.train_minibatch(*data[2])
        # next step hits the reconcile path (epoch bumped by the pull
        # stamps / fenced push responses); the queued dead-incarnation
        # pushes drop, nothing mis-applies.
        trainer.train_minibatch(*data[3])
        trainer.drain_pushes()
        fenced = servicers[0].counters["push_gen_rejected"]
        accepted = servicers[0].counters["push_accepted"] - accepted_before
        assert fenced >= 1
        # Every one of the 3 post-restart pushes either fenced or was
        # stamped AFTER a reconcile re-synced local state — and the
        # restored version advanced by exactly the accepted ones: a
        # dead-incarnation push slipping through would break the
        # accounting.
        assert fenced + accepted == 3
        assert servicers[0]._params.version == v_applied + accepted, (
            "a dead-incarnation push was applied to restored state "
            "(or a drop surfaced as a staleness retry)"
        )
        assert ("emb", b"sentinel") not in trainer._prefetched
        assert trainer._seen_gen_epoch == client.generation_epoch
        assert trainer.timing.counters().get("ps_reconcile", 0) >= 1
        trainer.close()
    finally:
        stop_all(servers)


def test_uninitialized_relaunch_reseeded_mid_run():
    """A shard that comes back with NO restorable checkpoint re-enters
    the uninitialized state; the reconcile path re-seeds it from the
    local model (push-to-init) instead of wedging pulls."""
    from elasticdl_tpu.ps.parameters import Parameters

    spec = make_spec()
    client, servicers, servers = start_ps(num_ps=1, generation=1)
    try:
        trainer = ParameterServerTrainer(spec, client, batch_size=32,
                                         get_model_steps=4)
        data = make_batches(spec)
        trainer.train_minibatch(*data[0])

        # Relaunch with empty state on the same port.
        fresh = Parameters()
        servicers[0]._params = fresh
        simulate_restart(servicers[0], generation=2)
        with pytest.raises(GradientsRejected):
            trainer.train_minibatch(*data[1])
        assert fresh.initialized, "reconcile did not re-seed the shard"
        loss, _ = trainer.train_minibatch(*data[1])
        assert np.isfinite(loss)
    finally:
        stop_all(servers)


# -- coordinated checkpoints --------------------------------------------


def test_truncate_shard_after_removes_abandoned_timeline(tmp_path):
    saver = CheckpointSaver(str(tmp_path))
    for v in (8, 16):
        saver.save(v, dense={"a": np.full(1, v, np.float32),
                             "b": np.full(1, v, np.float32)},
                   num_shards=2)
    # Shard 0 raced ahead on the dead timeline before the crash.
    saver.save_shard(24, 0, 2, dense={"a": np.full(1, 99, np.float32)})
    victims = saver.truncate_shard_after(16, 0, 2)
    assert victims == [24]
    assert saver.shard_versions(0, 2) == [8, 16]
    # Committed labels untouched.
    assert saver.latest_version() == 16


def test_servicer_checkpoint_failure_surfaces(tmp_path):
    """A failed save bumps ps_ckpt_failed and durable_version stays at
    the last version actually on disk, so the report to the master
    carries the TRUE durable mark."""
    client, servicers, servers = start_ps(
        num_ps=1, generation=1,
        checkpoint_saver=CheckpointSaver(str(tmp_path)),
        checkpoint_steps=1,
    )
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        client.push_gradients({"w": np.full(4, 0.5, np.float32)},
                              version=0)
        assert servicers[0].durable_version == 1
        # Break the checkpoint dir: point it UNDER a regular file, so
        # the save's makedirs raises (chmod tricks don't stop root).
        blocker = os.path.join(str(tmp_path), "blocker")
        with open(blocker, "w") as fh:
            fh.write("x")
        servicers[0]._checkpoint_saver._dir = os.path.join(
            blocker, "nested"
        )
        client.push_gradients({"w": np.full(4, 0.5, np.float32)},
                              version=1)
        assert servicers[0].counters["ps_ckpt_failed"] >= 1
        assert servicers[0].durable_version == 1
    finally:
        stop_all(servers)


def test_master_tracks_commit_mark_and_rollback():
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_manager import TaskManager

    ms = MasterServicer(TaskManager(training_shards=[],
                                    records_per_task=1))

    def report(ps_id, version, generation, durable):
        ms.report_version(pb.ReportVersionRequest(
            model_version=version, is_ps=True, ps_id=ps_id,
            generation=generation, durable_version=durable,
        ))

    assert ms.ps_commit_mark() is None
    report(0, 16, 1, 16)
    report(1, 16, 1, 8)
    # Commit mark = cross-shard MIN of durable versions.
    assert ms.ps_commit_mark() == 8
    state = ms.ps_state()
    assert state[0]["generation"] == 1 and state[1]["durable_version"] == 8
    # Shard 0 relaunches restored at 8: its durable mark must move
    # BACK with it (not max-folded) — recovery would really lose the
    # gap.
    report(0, 8, 2, 8)
    assert ms.ps_state()[0]["generation"] == 2
    assert ms.ps_commit_mark() == 8
    # A DELAYED report from the dead incarnation (outage-riding retry
    # landing late) must not float the mark back up: its durable file
    # may have been truncated by the restore.
    report(0, 16, 1, 16)
    assert ms.ps_state()[0]["durable_version"] == 8
    assert ms.ps_commit_mark() == 8
    report(1, 24, 1, 24)
    report(0, 24, 2, 24)
    assert ms.ps_commit_mark() == 24
    # Plain worker reports leave the PS plane alone.
    ms.report_version(pb.ReportVersionRequest(model_version=99))
    assert 99 not in ms.ps_state()


# -- PSManager lifecycle -------------------------------------------------


class _FakeProc:
    def __init__(self, code=0, term_hangs=False, dead=False):
        self._code = code
        self.pid = 4242
        self.terminated = False
        self.killed = False
        self._term_hangs = term_hangs
        self._dead = dead

    def poll(self):
        if self._dead or self.killed or (
            self.terminated and not self._term_hangs
        ):
            return self._code
        return None

    def wait(self, timeout=None):
        import subprocess

        if self.poll() is None:
            raise subprocess.TimeoutExpired("fake", timeout)
        return self._code

    def terminate(self):
        self.terminated = True

    def kill(self):
        self.killed = True


def _manager(**kwargs):
    from elasticdl_tpu.master.ps_manager import PSManager

    kwargs.setdefault("num_ps", 1)
    kwargs.setdefault("opt_type", "sgd")
    kwargs.setdefault("opt_args", "learning_rate=0.1")
    return PSManager(**kwargs)


def test_relaunch_budget_decays_after_healthy_uptime(monkeypatch):
    import time as _time

    mgr = _manager(max_relaunch=2, relaunch_decay_secs=100.0)
    launches = []
    monkeypatch.setattr(
        mgr, "_launch", lambda ps_id, restore=False:
        launches.append((ps_id, restore))
    )
    now = _time.monotonic()
    # Two crashes in a row: budget counts up.
    mgr._launched_at[0] = now
    mgr._watch(0, _FakeProc(code=9, dead=True))
    mgr._watch(0, _FakeProc(code=9, dead=True))
    assert mgr._relaunches[0] == 2 and len(launches) == 2
    # Budget would be spent — but this death follows a LONG healthy
    # uptime, so the count resets and the relaunch proceeds.
    mgr._launched_at[0] = now - 500.0
    mgr._watch(0, _FakeProc(code=9, dead=True))
    assert mgr._relaunches[0] == 1 and len(launches) == 3
    # Fast crash right after: counts from the fresh budget.
    mgr._launched_at[0] = _time.monotonic()
    mgr._watch(0, _FakeProc(code=9, dead=True))
    assert mgr._relaunches[0] == 2 and len(launches) == 4
    # And the next fast crash exhausts it.
    mgr._watch(0, _FakeProc(code=9, dead=True))
    assert len(launches) == 4


def test_stop_escalates_terminate_to_kill(monkeypatch):
    mgr = _manager()
    monkeypatch.setattr(mgr, "STOP_GRACE_SECS", 0.05)
    monkeypatch.setattr(mgr, "STOP_KILL_WAIT_SECS", 0.05)
    polite = _FakeProc()
    wedged = _FakeProc(term_hangs=True)
    mgr._procs = {0: polite, 1: wedged}
    mgr.stop()
    assert polite.terminated and not polite.killed
    assert wedged.terminated and wedged.killed
    assert mgr._stopped.is_set()


def test_launch_args_carry_generation_and_fault_spec():
    mgr = _manager(
        checkpoint_dir="/ckpt", checkpoint_steps=8,
        ps_fault_spec="push_gradients:every=5,code=UNAVAILABLE",
    )
    mgr._launch_counts[0] = 2  # two launches so far
    args = mgr._args(0, restore=True, generation=3)
    assert args[args.index("--generation") + 1] == "3"
    assert args[args.index("--rpc_fault_spec") + 1] == (
        "push_gradients:every=5,code=UNAVAILABLE"
    )
    assert "--checkpoint_dir_for_init" in args


# -- retry policy --------------------------------------------------------


def test_ps_rpc_policy_env_budget(monkeypatch):
    monkeypatch.setenv("ELASTICDL_RPC_DEADLINE_SECS", "7")
    assert ps_rpc_policy().deadline_secs == 7.0
    assert ps_rpc_policy(deadline_secs=3.0).deadline_secs == 3.0


def test_proto_generation_fields_roundtrip():
    req = pb.PushGradientsRequest(generation=5)
    assert pb.PushGradientsRequest.FromString(
        req.SerializeToString()
    ).generation == 5
    rv = pb.ReportVersionRequest(
        model_version=4, is_ps=True, ps_id=1, generation=2,
        durable_version=3,
    )
    back = pb.ReportVersionRequest.FromString(rv.SerializeToString())
    assert (back.is_ps, back.ps_id, back.generation,
            back.durable_version) == (True, 1, 2, 3)
