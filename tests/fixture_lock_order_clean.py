"""Clean-ordering counterpart to fixture_abba: two locks, one global
acquisition order (north before south, always).  EL005 must stay
silent on this module, and the tracer must observe edges in only one
direction (no cycle)."""

import threading


class CourierNorth:
    def __init__(self, courier_south=None):
        self._lock = threading.Lock()
        self._courier_south = courier_south
        self._handled = 0

    def handoff(self):
        # North's lock is always the OUTER lock: N -> S only.
        with self._lock:
            self._handled += 1
            self._courier_south.accept()


class CourierSouth:
    def __init__(self):
        self._lock = threading.Lock()
        self._accepted = 0

    def accept(self):
        with self._lock:
            self._accepted += 1


def build_pair():
    south = CourierSouth()
    north = CourierNorth(courier_south=south)
    return north, south


def drive_sequentially(north, south):
    north.handoff()
    south.accept()
