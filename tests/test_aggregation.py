"""The model-aggregation tier (elasticdl_tpu/aggregation/): ingest
monotonicity, window aggregation math, atomic publish, freshness SLO
accounting, retention GC floors, the trainer's continuous-export hook,
and the ContinuousExporter's program reuse."""

import json
import os

import numpy as np
import pytest

from elasticdl_tpu.aggregation import ModelAggregator
from elasticdl_tpu.serving.export import ContinuousExporter
from elasticdl_tpu.serving.loader import (
    list_versions,
    load_servable,
)


def _apply(p, x):
    return x @ p["w"]


def _exporter(base):
    return ContinuousExporter(str(base), model_name="lin",
                              platforms=("cpu",))


def _export(ce, version, value):
    ce.export(version, _apply,
              {"w": np.full((4, 2), value, np.float32)},
              np.zeros((1, 4), np.float32))


def _published_value(pub):
    model = load_servable(str(pub))
    out = np.asarray(model.predict(np.ones((1, 4), np.float32)))
    return float(out[0, 0]) / 4.0


def test_ingest_is_version_monotone(tmp_path):
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(src)
    agg = ModelAggregator(str(src), str(pub), window=4)
    _export(ce, 10, 1.0)
    _export(ce, 20, 2.0)
    assert agg.ingest_once() == [10, 20]
    # A re-formed world's out-of-order export: a SECOND exporter (new
    # program cache, like a relaunched worker 0) lands version 15.
    _export(_exporter(src), 15, 9.0)
    assert agg.ingest_once() == []
    stats = agg.stats()
    assert stats["counters"]["stale_exports_skipped"] == 1
    assert stats["last_ingested_version"] == 20
    # Counted once, not once per scan.
    agg.ingest_once()
    assert agg.stats()["counters"]["stale_exports_skipped"] == 1


def test_mean_and_ema_window_math(tmp_path):
    src = tmp_path / "src"
    ce = _exporter(src)
    for version, value in ((1, 1.0), (2, 2.0), (3, 3.0)):
        _export(ce, version, value)

    mean = ModelAggregator(str(src), str(tmp_path / "mean"),
                           window=3, mode="mean")
    mean.ingest_once()
    mean.publish()
    assert _published_value(tmp_path / "mean") == pytest.approx(2.0)

    # EMA decay 0.5 over [1, 2, 3]: weights 0.25/0.5/1 normalized ->
    # (0.25*1 + 0.5*2 + 1*3) / 1.75
    ema = ModelAggregator(str(src), str(tmp_path / "ema"),
                          window=3, mode="ema", ema_decay=0.5)
    ema.ingest_once()
    ema.publish()
    assert _published_value(tmp_path / "ema") == pytest.approx(
        (0.25 * 1 + 0.5 * 2 + 1 * 3) / 1.75)

    latest = ModelAggregator(str(src), str(tmp_path / "latest"),
                             window=3, mode="latest")
    latest.ingest_once()
    latest.publish()
    assert _published_value(tmp_path / "latest") == pytest.approx(3.0)


def test_window_caps_membership(tmp_path):
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(src)
    for version, value in ((1, 10.0), (2, 1.0), (3, 1.0)):
        _export(ce, version, value)
    agg = ModelAggregator(str(src), str(pub), window=2, mode="mean")
    agg.ingest_once()
    agg.publish()
    # Version 1 (value 10) fell off the 2-wide window.
    assert _published_value(pub) == pytest.approx(1.0)


def test_publish_is_atomic_and_carries_aggregation_manifest(tmp_path):
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(src)
    _export(ce, 1, 1.0)
    _export(ce, 2, 2.0)
    agg = ModelAggregator(str(src), str(pub), window=2, mode="mean")
    agg.ingest_once()
    version, freshness = agg.publish()
    assert version == 2 and freshness >= 0.0
    assert sorted(os.listdir(pub)) == ["2"]  # no staging leftovers
    with open(pub / "2" / "manifest.json") as f:
        manifest = json.load(f)
    assert manifest["version"] == 2
    assert manifest["aggregation"]["mode"] == "mean"
    assert manifest["aggregation"]["source_versions"] == [1, 2]
    assert manifest["format"].startswith("elasticdl_tpu_servable")


def test_publish_due_throttle_and_slo_miss_counting(tmp_path):
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(src)
    _export(ce, 1, 1.0)
    agg = ModelAggregator(str(src), str(pub), window=2,
                          freshness_slo_secs=0.0,  # every publish late
                          min_publish_interval_secs=3600.0)
    agg.ingest_once()
    assert agg.publish_due()  # first publish never throttled
    agg.publish()
    assert agg.stats()["counters"]["slo_misses"] == 1
    _export(ce, 2, 2.0)
    agg.ingest_once()
    # New ingest waiting, but inside the throttle interval.
    assert not agg.publish_due()
    assert agg.publish_due(now=agg._last_publish_at + 3601)


def test_retention_gc_floors_at_committed(tmp_path):
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(src)
    agg = ModelAggregator(str(src), str(pub), window=1,
                          export_keep=2)
    for version in (1, 2, 3, 4):
        _export(ce, version, float(version))
        agg.ingest_once()
        agg.publish()
    assert list_versions(str(pub)) == [1, 2, 3, 4]
    # Unknown committed floor: nothing is removed.
    assert agg.gc_published(committed_floor=None) == []
    # Committed = 2: version 2 and newer are protected even though
    # keep=2 would otherwise allow removing 2.
    assert agg.gc_published(committed_floor=2) == [1]
    assert list_versions(str(pub)) == [2, 3, 4]
    # Committed = 4: keep the newest 2, floor protects nothing extra.
    assert agg.gc_published(committed_floor=4) == [2]
    assert list_versions(str(pub)) == [3, 4]


def test_broken_export_is_skipped_then_superseded(tmp_path):
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(src)
    _export(ce, 1, 1.0)
    # A "complete" version whose payload is unreadable.
    os.makedirs(src / "2")
    (src / "2" / "manifest.json").write_text("{}")
    agg = ModelAggregator(str(src), str(pub), window=4)
    assert agg.ingest_once() == [1]
    assert agg.stats()["counters"]["ingest_errors"] == 1
    # A later good version supersedes it; the broken one becomes a
    # stale skip, not a permanent retry.
    _export(ce, 3, 3.0)
    assert agg.ingest_once() == [3]
    agg.ingest_once()
    assert agg.stats()["counters"]["stale_exports_skipped"] == 1


def test_continuous_exporter_reuses_program(tmp_path):
    src = tmp_path / "src"
    ce = _exporter(src)
    _export(ce, 1, 1.0)
    _export(ce, 2, 2.0)
    with open(src / "1" / "model.stablehlo", "rb") as f:
        program1 = f.read()
    with open(src / "2" / "model.stablehlo", "rb") as f:
        program2 = f.read()
    assert program1 == program2  # traced once, bytes reused
    # ...and the reused-program export still predicts correctly.
    model = load_servable(str(src / "2"))
    out = np.asarray(model.predict(np.ones((1, 4), np.float32)))
    assert out[0, 0] == pytest.approx(8.0)
    # A changed parameter tree re-traces instead of mis-serving.
    ce.export(3, lambda p, x: x @ p["w2"],
              {"w2": np.full((4, 3), 1.0, np.float32)},
              np.zeros((1, 4), np.float32))
    model3 = load_servable(str(src / "3"))
    assert np.asarray(
        model3.predict(np.ones((1, 4), np.float32))).shape == (1, 3)


def test_continuous_exporter_source_retention(tmp_path):
    src = tmp_path / "src"
    ce = ContinuousExporter(str(src), model_name="lin",
                            platforms=("cpu",), keep=3)
    for version in range(1, 7):
        _export(ce, version, float(version))
    assert list_versions(str(src)) == [4, 5, 6]
    unbounded = ContinuousExporter(str(tmp_path / "all"),
                                   model_name="lin",
                                   platforms=("cpu",), keep=0)
    for version in (1, 2):
        _export(unbounded, version, 1.0)
    assert list_versions(str(tmp_path / "all")) == [1, 2]


def test_continuous_exporter_reuse_path_manifest_is_truthful(
        tmp_path):
    """The program-reuse export must write the SAME encodings the full
    export would — and its manifest must describe this payload, not
    the cached template's."""
    src = tmp_path / "src"
    ce = ContinuousExporter(str(src), model_name="lin",
                            platforms=("cpu",), quantize="int8")
    table = (np.arange(256), np.ones((256, 16), np.float32))

    def export_with_table(version):
        ce.export(version, _apply,
                  {"w": np.full((4, 2), 1.0, np.float32)},
                  np.zeros((1, 4), np.float32),
                  embeddings={"users": table})

    export_with_table(1)
    export_with_table(2)  # the reuse path
    for version in (1, 2):
        with open(src / str(version) / "manifest.json") as f:
            manifest = json.load(f)
        with np.load(src / str(version) / "model.npz") as z:
            keys = set(z.files)
        assert manifest["format"].startswith("int8-emb+")
        assert "emb:users" in manifest["quantized_int8"]
        assert manifest["embedding_tables"] == ["users"]
        assert "q8emb/users" in keys and "emb_vals/users" not in keys
    # And the loader round-trips the reused-program export.
    model = load_servable(str(src / "2"))
    assert np.allclose(model.lookup_embedding("users", [3]), 1.0,
                       atol=0.02)


def test_trainer_export_hook_cadence(tmp_path):
    from elasticdl_tpu.models import mnist
    from elasticdl_tpu.worker.collective_trainer import (
        CollectiveTrainer,
    )

    src = tmp_path / "src"
    spec = mnist.model_spec(learning_rate=1e-3)
    ce = ContinuousExporter(str(src), model_name="mnist",
                            platforms=("cpu",))
    trainer = CollectiveTrainer(spec, batch_size=16, exporter=ce,
                                export_steps=3)
    xs, ys = mnist.synthetic_data(n=16)
    for _ in range(7):
        trainer.train_minibatch(xs, ys)
    assert trainer.steps_to_boundary() == 2  # next export at 9
    trainer.flush_checkpoints()  # joins the async export writes
    assert list_versions(str(src)) == [3, 6]
    model = load_servable(str(src))
    assert model.manifest["version"] == 6
    assert np.asarray(model.predict(xs[:2])).shape == (2, 10)
    assert trainer.timing.counters()["servable_exports"] == 2


def test_worker_main_guard_is_worker_zero_only(tmp_path):
    from elasticdl_tpu.models import mnist
    from elasticdl_tpu.utils.args import parse_worker_args
    from elasticdl_tpu.worker.main import _build_collective_trainer

    spec = mnist.model_spec(learning_rate=1e-3)
    args = parse_worker_args([
        "--export_base", str(tmp_path / "src"),
        "--export_steps", "4",
    ])
    chief = _build_collective_trainer(args, None, spec, worker_id=0)
    follower = _build_collective_trainer(args, None, spec,
                                         worker_id=1)
    assert chief._export_steps == 4
    assert chief._exporter is not None
    assert follower._export_steps == 0


def test_republish_after_restart_is_an_idempotent_skip(tmp_path):
    """A restarted aggregator (or worker) replaying its state must not
    rewrite a complete published version — the swap path is not
    single-rename atomic, and the fleet may have committed that dir."""
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(src)
    _export(ce, 5, 2.0)
    agg = ModelAggregator(str(src), str(pub), window=2, mode="mean")
    agg.ingest_once()
    agg.publish()
    before = (pub / "5" / "model.npz").read_bytes()
    # Fresh aggregator, same dirs (restart): re-ingests and re-reaches
    # publish for the same version.
    agg2 = ModelAggregator(str(src), str(pub), window=2,
                           mode="latest")
    agg2.ingest_once()
    version, _ = agg2.publish()
    assert version == 5
    assert agg2.stats()["counters"]["republish_skipped"] == 1
    assert (pub / "5" / "model.npz").read_bytes() == before
    # Same rule on the trainer side: a relaunched worker re-exporting
    # its last version leaves the complete dir untouched.
    ce2 = _exporter(src)
    manifest = ce2.export(5, _apply,
                          {"w": np.full((4, 2), 99.0, np.float32)},
                          np.zeros((1, 4), np.float32))
    assert manifest["version"] == 5
    assert _published_value(src / "5") == pytest.approx(2.0)


def test_program_cache_keyed_on_shapes_not_names(tmp_path):
    """A resized layer keeps its flat name; the aggregator must
    publish the re-traced program its export carries, not the cached
    one for the old shape."""
    src, pub = tmp_path / "src", tmp_path / "pub"
    agg = ModelAggregator(str(src), str(pub), window=1,
                          mode="latest")
    ce = _exporter(src)
    _export(ce, 1, 1.0)
    agg.ingest_once()
    agg.publish()
    # Same flat name "w", NEW shape (4, 3): a fresh exporter re-traces.
    ContinuousExporter(str(src), model_name="lin",
                       platforms=("cpu",)).export(
        2, _apply, {"w": np.full((4, 3), 1.0, np.float32)},
        np.zeros((1, 4), np.float32))
    agg.ingest_once()
    agg.publish()
    model = load_servable(str(pub / "2"))
    out = np.asarray(model.predict(np.ones((1, 4), np.float32)))
    assert out.shape == (1, 3)  # the new-shape program, not the stale one


def test_bad_mode_and_decay_refused(tmp_path):
    with pytest.raises(ValueError, match="mode"):
        ModelAggregator(str(tmp_path), str(tmp_path), mode="median")
    with pytest.raises(ValueError, match="ema_decay"):
        ModelAggregator(str(tmp_path), str(tmp_path), ema_decay=1.5)


# -- streaming frames (the binary wire format, docs/serving.md) -----------


def test_frame_wire_source_ingests_and_publishes(tmp_path):
    """ContinuousExporter(wire_format="frame") writes model.frame
    instead of model.npz; the aggregator ingests it through the same
    loop and publishes a plain npz servable the fleet loader reads —
    while the standalone loader refuses the frame-format SOURCE dir
    loudly (it is the aggregator's input, not a servable)."""
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = ContinuousExporter(str(src), model_name="lin",
                            platforms=("cpu",), wire_format="frame")
    for version, value in ((1, 1.0), (2, 3.0)):
        ce.export(version, _apply,
                  {"w": np.full((4, 2), value, np.float32)},
                  np.zeros((1, 4), np.float32))
    assert os.path.isfile(str(src / "1" / "model.frame"))
    assert not os.path.exists(str(src / "1" / "model.npz"))
    with open(str(src / "2" / "manifest.json")) as f:
        assert json.load(f)["format"].startswith("frame+")
    with pytest.raises(ValueError, match="format"):
        load_servable(str(src / "2"))
    agg = ModelAggregator(str(src), str(pub), window=2, mode="mean")
    assert agg.ingest_once() == [1, 2]
    version, _ = agg.publish()
    assert version == 2
    assert _published_value(pub / "2") == pytest.approx(2.0)


def test_streamed_frame_ingest_no_filesystem(tmp_path):
    """frame_bytes -> ingest_frame: a trainer version reaches the
    aggregator with no export directory at all.  The program rides
    in-band on the first frame only; stale frames skip monotonically;
    the publish is byte-compatible with the file path."""
    from elasticdl_tpu.serving.export import servable_from_frame

    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(tmp_path / "unused")
    agg = ModelAggregator(str(src), str(pub), window=2, mode="latest")

    def frame(version, value, **kw):
        return ce.frame_bytes(
            version, _apply,
            {"w": np.full((4, 2), value, np.float32)},
            np.zeros((1, 4), np.float32), **kw)

    first = frame(1, 1.0)
    assert servable_from_frame(first)[3] is not None  # program rides
    steady = frame(2, 2.0)
    assert servable_from_frame(steady)[3] is None     # weights only
    assert agg.ingest_frame(first) == 1
    assert agg.ingest_frame(steady) == 2
    assert agg.ingest_frame(first) is None            # stale: skipped
    stats = agg.stats()
    assert stats["counters"]["stale_exports_skipped"] == 1
    assert stats["counters"]["ingested_frames"] == 2
    version, _ = agg.publish()
    assert version == 2
    assert _published_value(pub / "2") == pytest.approx(2.0)


def test_streamed_tree_change_without_program_fails_loudly(tmp_path):
    src, pub = tmp_path / "src", tmp_path / "pub"
    ce = _exporter(tmp_path / "unused")
    agg = ModelAggregator(str(src), str(pub), window=1,
                          mode="latest")
    agg.ingest_frame(ce.frame_bytes(
        1, _apply, {"w": np.full((4, 2), 1.0, np.float32)},
        np.zeros((1, 4), np.float32)))
    agg.publish()
    # A NEW tree whose priming frame was suppressed: the publish must
    # refuse instead of serving the old program with new weights.
    blob = ce.frame_bytes(
        2, lambda p, x: x @ p["w2"],
        {"w2": np.full((4, 3), 1.0, np.float32)},
        np.zeros((1, 4), np.float32), include_program=False)
    agg.ingest_frame(blob)
    with pytest.raises(RuntimeError, match="include_program"):
        agg.publish()
    # Re-priming with the program recovers.
    agg2 = ModelAggregator(str(src), str(pub), window=1,
                           mode="latest")
    agg2.ingest_frame(ce.frame_bytes(
        3, lambda p, x: x @ p["w2"],
        {"w2": np.full((4, 3), 1.0, np.float32)},
        np.zeros((1, 4), np.float32), include_program=True))
    version, _ = agg2.publish()
    model = load_servable(str(pub / "3"))
    assert np.asarray(
        model.predict(np.ones((1, 4), np.float32))).shape == (1, 3)


def test_exporter_wire_format_validation(tmp_path):
    with pytest.raises(ValueError, match="wire_format"):
        ContinuousExporter(str(tmp_path), wire_format="zip")
