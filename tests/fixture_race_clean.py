"""Clean counterpart to ``fixture_race.py`` — same two thread roots,
same shared attributes, zero findings.

Every read-modify-write sits under ``self._lock`` (one guard common to
both roots), and ``_snapshot`` demonstrates the sanctioned lock-free
idiom EL011 must NOT flag: an immutable tuple published by a single
reference assignment (atomic under the GIL), read by the other root
without the lock.  If EL011 or the runtime sampler ever fires on this
module, the rule has drifted into crying wolf.
"""

import threading
from concurrent.futures import ThreadPoolExecutor


class GuardedTelemetryHub:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=2)
        self._thread = None
        self._totals = {}
        self._total_reports = 0
        self._snapshot = ()

    def start(self):
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True)
        self._thread.start()

    def submit_report(self, key):
        return self._pool.submit(self._ingest, key)

    def _flush_loop(self):
        while not self._stop.wait(0.01):
            self._flush_once()

    def _flush_once(self):
        with self._lock:
            self._total_reports += 1
            self._totals["flushed"] = len(self._totals)
            snap = tuple(sorted(self._totals.items()))
        # atomic publication: plain rebind of an immutable value —
        # readers take the current version without the lock
        self._snapshot = snap

    def _ingest(self, key):
        with self._lock:
            self._total_reports += 1
            self._totals[key] = self._totals.get(key, 0) + 1
        return self._snapshot

    def close(self):
        self._stop.set()
        self._pool.shutdown(wait=True)


def drive_clean_from_two_threads(hub):
    """Mirror of fixture_race.drive_race_from_two_threads: both roots
    touch the counters from distinct threads, every time holding the
    lock — the sampler must confirm nothing.  Warm-up submit first so
    the pool worker's ident cannot be recycled onto the flusher (see
    the racy fixture's docstring)."""
    hub.submit_report("warm").result()
    flusher = threading.Thread(target=hub._flush_once)
    flusher.start()
    flusher.join()
    hub.submit_report("drill").result()
