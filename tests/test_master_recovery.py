"""Master crash-restart recovery: journal round-trips, replay-safe
task accounting, rendezvous epoch monotonicity, the unified retry
policy, and deterministic RPC fault injection
(docs/master_recovery.md)."""

import os
import threading
import time
from types import SimpleNamespace

import grpc
import pytest

from elasticdl_tpu.master.journal import (
    JournalWriter,
    journal_path,
    replay_journal,
)
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.servicer import (
    MasterServicer,
    create_master_service,
)
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import grpc_utils
from elasticdl_tpu.utils.grpc_utils import (
    FaultInjectionInterceptor,
    FaultSpec,
)
from elasticdl_tpu.utils.retry import RetryPolicy
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.data_shard_service import DataShardService
from elasticdl_tpu.worker.master_client import MasterClient


class FakeRpcError(grpc.RpcError):
    def __init__(self, code=grpc.StatusCode.UNAVAILABLE):
        self._code = code

    def code(self):
        return self._code


def make_tm(journal_dir=None, **kw):
    defaults = dict(
        training_shards=[("f", 0, 120)], records_per_task=30,
        num_epochs=1,
    )
    defaults.update(kw)
    tm = TaskManager(**defaults)
    if journal_dir is not None:
        tm.attach_journal(JournalWriter(journal_dir), bootstrap=True)
    return tm


def restart_tm(journal_dir, **kw):
    """The master/main.py restart flow, in miniature."""
    state = replay_journal(journal_dir)
    assert state is not None
    tm = make_tm(journal_dir=None, **kw)
    tm.restore_from_journal(state)
    writer = JournalWriter(journal_dir)
    writer.append({"ev": "restart"})
    tm.attach_journal(writer, bootstrap=False)
    return tm, state


# -- journal framing ---------------------------------------------------------

def test_journal_append_replay_roundtrip(tmp_path):
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    w.append({"ev": "meta", "job": {"num_epochs": 2}})
    w.append({"ev": "task", "id": 1, "type": int(pb.TRAINING),
              "name": "f", "start": 0, "end": 30, "mv": -1})
    w.append({"ev": "dispatch", "id": 1, "w": 0})
    w.append({"ev": "done", "id": 1})
    w.append({"ev": "batch", "w": 0, "n": 30})
    w.append({"ev": "version", "v": 7})
    w.append({"ev": "rdzv", "n": 3, "hosts": ["h0"]})
    w.close()
    state = replay_journal(jdir)
    assert state.meta == {"num_epochs": 2}
    assert state.status[1] == "done"
    assert state.completed_counts[int(pb.TRAINING)] == 1
    assert state.worker_records[0] == 30
    assert state.records_done == 30
    assert state.model_version == 7
    assert state.rendezvous_id == 3
    assert state.max_task_id == 1


def test_truncated_tail_dropped_loudly_not_crash(tmp_path):
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    w.append({"ev": "task", "id": 1, "type": int(pb.TRAINING),
              "name": "f", "start": 0, "end": 30, "mv": -1})
    w.append({"ev": "done", "id": 1})
    w.close()
    path = journal_path(jdir)
    intact = os.path.getsize(path)
    # Torn write: half a frame of garbage at the tail.
    with open(path, "ab") as fh:
        fh.write(b"\x40\x00\x00\x00\xde\xad\xbe\xefpartial")
    import logging as _logging

    messages = []
    handler = _logging.Handler()
    handler.emit = lambda rec: messages.append(rec.getMessage())
    journal_logger = _logging.getLogger("elasticdl_tpu.master.journal")
    journal_logger.addHandler(handler)
    try:
        state = replay_journal(jdir)
    finally:
        journal_logger.removeHandler(handler)
    assert state is not None and state.status[1] == "done"
    assert any("truncated" in m for m in messages)  # dropped LOUDLY
    # Reopening the writer truncates back to the last valid frame so
    # appends never land after garbage.
    w2 = JournalWriter(jdir)
    assert os.path.getsize(path) == intact
    w2.append({"ev": "fail", "id": 1, "perm": False, "retries": 1})
    w2.close()
    state2 = replay_journal(jdir)
    assert state2.status[1] == "done"  # done is absorbing


def test_sched_records_roundtrip(tmp_path):
    """Scheduler decisions (docs/scheduler.md) replay to the exact
    admission states and worker->job assignment map the crashed master
    had made durable — including a mid-resize kill, where the decision
    record landed but the drain's effects did not (they are
    reconstructed by the per-job restart requeue anyway)."""
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    w.append({"ev": "sched", "op": "submit", "job": 1, "name": "a",
              "min": 1, "max": 3, "weight": 1.0})
    w.append({"ev": "sched", "op": "admit", "job": 1})
    w.append({"ev": "sched", "op": "submit", "job": 2, "name": "b",
              "min": 1, "max": 0, "weight": 2.0})
    w.append({"ev": "sched", "op": "admit", "job": 2})
    w.append({"ev": "sched", "op": "assign", "w": 0, "job": 1,
              "prev": 0})
    w.append({"ev": "sched", "op": "assign", "w": 1, "job": 2,
              "prev": 0})
    w.append({"ev": "sched", "op": "finish", "job": 1})
    # the mid-resize decision: worker 0 moved a -> b, then SIGKILL
    w.append({"ev": "sched", "op": "assign", "w": 0, "job": 2,
              "prev": 1})
    w.close()
    state = replay_journal(jdir)
    assert state.sched_assignments == {0: 2, 1: 2}
    assert state.sched_jobs[1] == {"name": "a", "state": "finished"}
    assert state.sched_jobs[2] == {"name": "b", "state": "running"}
    assert state.sched_decisions["assign"] == 3
    assert state.sched_decisions["finish"] == 1


def test_sched_release_and_unknown_op_tolerated(tmp_path):
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    w.append({"ev": "sched", "op": "submit", "job": 1, "name": "a"})
    w.append({"ev": "sched", "op": "assign", "w": 3, "job": 1,
              "prev": 0})
    w.append({"ev": "sched", "op": "release", "w": 3, "job": 1,
              "reason": "exit"})
    w.append({"ev": "sched", "op": "frobnicate"})   # future record
    w.close()
    state = replay_journal(jdir)
    assert state.sched_assignments == {}
    assert state.sched_jobs[1]["state"] == "pending"


def test_sched_mid_resize_torn_tail_keeps_committed_schedule(tmp_path):
    """A torn frame exactly at the resize decision leaves the
    PREVIOUS schedule — never half a decision."""
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    w.append({"ev": "sched", "op": "submit", "job": 1, "name": "a"})
    w.append({"ev": "sched", "op": "admit", "job": 1})
    w.append({"ev": "sched", "op": "assign", "w": 0, "job": 1,
              "prev": 0})
    w.close()
    with open(journal_path(jdir), "ab") as fh:
        fh.write(b"\x30\x00\x00\x00\x99\x99\x99\x99half-a-decision")
    state = replay_journal(jdir)
    assert state.sched_assignments == {0: 1}
    assert state.sched_jobs[1]["state"] == "running"


# -- task manager restart ----------------------------------------------------

def test_restart_requeues_inflight_and_resumes_exactly(tmp_path):
    jdir = str(tmp_path)
    tm1 = make_tm(journal_dir=jdir)  # 4 tasks of 30
    t_done = tm1.get(0)
    t_inflight = tm1.get(1)
    tm1.report(t_done.id, True)
    tm1._journal.close()  # crash

    tm2, state = restart_tm(jdir)
    counts = tm2.counts()
    assert counts["completed"][pb.TRAINING] == 1
    assert counts["doing"] == 0
    assert counts["todo"] == 3  # 2 untouched + the in-flight requeued
    # The requeued in-flight task dispatches FIRST.
    nxt = tm2.get(2)
    assert nxt.id == t_inflight.id
    # Drain the job: exactly 4 completions total, nothing lost/doubled.
    tm2.report(nxt.id, True)
    while True:
        t = tm2.get(2)
        if t is None:
            break
        tm2.report(t.id, True)
    assert tm2.finished()
    assert tm2.counts()["completed"][pb.TRAINING] == 4


def test_rereport_of_journaled_task_is_idempotent(tmp_path):
    jdir = str(tmp_path)
    tm1 = make_tm(journal_dir=jdir)
    t = tm1.get(0)
    tm1.report(t.id, True)
    tm1._journal.close()

    tm2, _ = restart_tm(jdir)
    before = tm2.counts()["completed"][pb.TRAINING]
    # The worker's report RPC raced the crash; its retry lands here.
    result = tm2.report(t.id, True)
    assert result.ok
    assert tm2.counts()["completed"][pb.TRAINING] == before


def test_report_for_requeued_task_completes_from_todo(tmp_path):
    jdir = str(tmp_path)
    tm1 = make_tm(journal_dir=jdir)
    t = tm1.get(0)  # in flight at crash time
    tm1._journal.close()

    tm2, _ = restart_tm(jdir)
    assert tm2.counts()["todo"] == 4  # requeued
    # The worker rode out the outage and reports the task done.
    result = tm2.report(t.id, True)
    assert result.ok
    counts = tm2.counts()
    assert counts["completed"][pb.TRAINING] == 1
    assert counts["todo"] == 3  # never re-dispatched, no double work


def test_skip_records_flow_through_journal(tmp_path):
    jdir = str(tmp_path)
    tm1 = make_tm(journal_dir=jdir)
    tm1.skip_records(45)  # drops task 1 (30) + trims 15 off task 2
    tm1._journal.close()
    tm2, _ = restart_tm(jdir)
    t = tm2.get(0)
    assert t.shard.start == 45 and t.shard.end == 60
    assert tm2.counts()["completed"][pb.TRAINING] == 1


def test_task_retry_budget_survives_restart(tmp_path):
    jdir = str(tmp_path)
    tm1 = make_tm(journal_dir=jdir, max_task_retries=2,
                  training_shards=[("f", 0, 30)])
    t = tm1.get(0)
    tm1.report(t.id, False, "boom")  # retry 1 journaled
    tm1._journal.close()

    tm2, _ = restart_tm(jdir, max_task_retries=2,
                        training_shards=[("f", 0, 30)])
    t = tm2.get(0)
    tm2.report(t.id, False, "boom")  # retry 2
    t = tm2.get(0)
    result = tm2.report(t.id, False, "boom")  # budget exhausted
    assert result.permanent_failure
    assert tm2.counts()["failed"][pb.TRAINING] == 1


# -- rendezvous --------------------------------------------------------------

def test_rendezvous_epoch_monotonic_across_restart(tmp_path):
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    rs1 = RendezvousServer(grace_secs=0.0, journal=w)
    rs1.add_worker("h0")
    rank, size, epoch1, _ = rs1.get_comm_rank("h0")
    assert (rank, size) == (0, 1) and epoch1 == 1
    rs1.add_worker("h1")
    _, _, epoch2, _ = rs1.get_comm_rank("h0")
    assert epoch2 == 2
    w.close()  # crash

    state = replay_journal(jdir)
    assert state.rendezvous_id == 2
    w2 = JournalWriter(jdir)
    rs2 = RendezvousServer(
        grace_secs=0.0, journal=w2,
        initial_epoch=state.rendezvous_id + 1,
    )
    # A reconnecting worker sees rank=-1 at an id strictly above any
    # epoch it can hold -> it re-announces instead of assuming its old
    # world is live.
    rank, _, epoch, _ = rs2.get_comm_rank("h0")
    assert rank == -1 and epoch >= epoch2 + 1
    rs2.add_worker("h0")
    rs2.add_worker("h1")
    rank, size, epoch3, _ = rs2.get_comm_rank("h0")
    assert (rank, size) == (0, 2)
    assert epoch3 > epoch2  # strictly monotone across the crash
    w2.close()
    assert replay_journal(jdir).rendezvous_id == epoch3


class _RendezvousMasterClient:
    """Fake MasterClient driving a RendezvousServer directly (the two
    RPCs the controller's world management uses)."""

    def __init__(self, rs, host):
        self.rs = rs
        self.host = host

    def get_comm_rank(self):
        rank, size, rid, addr = self.rs.get_comm_rank(self.host)
        return SimpleNamespace(
            rank_id=rank, world_size=size, rendezvous_id=rid,
            coordinator_addr=addr,
        )

    def report_train_loop_status(self, status):
        if status == pb.LOOP_START:
            self.rs.add_worker(self.host)
        else:
            self.rs.remove_worker(self.host)


def test_controller_reannounces_at_unchanged_restart_epoch():
    """The worst-case restart: the master re-arms at journaled+1,
    which EQUALS the un-journaled epoch a surviving worker glimpsed
    just before the crash.  The survivor sees rank=-1 at an UNCHANGED
    id against an empty committed world — it must re-announce anyway
    (id-change detection alone would leave both sides waiting
    forever)."""
    from elasticdl_tpu.api.controller import ElasticCollectiveController

    rs1 = RendezvousServer(grace_secs=0.0)
    mc = _RendezvousMasterClient(rs1, "h0")
    ctrl = ElasticCollectiveController(mc, trainer=object(),
                                       check_secs=0.0)
    mc.report_train_loop_status(pb.LOOP_START)
    assert ctrl.init_world_if_needed(force=True)
    # epoch 2: glimpsed by the worker, but (simulated) never durable
    rs1.add_worker("h1")
    assert ctrl.init_world_if_needed(force=True)
    glimpsed = ctrl._rendezvous.rendezvous_id
    assert glimpsed == 2

    # master crash + restart: journal held only epoch 1, re-armed at
    # 1 + 1 == the glimpsed id, committed world empty
    rs2 = RendezvousServer(grace_secs=0.0, initial_epoch=glimpsed)
    mc.rs = rs2
    # first check: rank=-1, id unchanged -> must still announce
    assert not ctrl.init_world_if_needed(force=True)
    assert "h0" in rs2._next_hosts
    # next check commits the post-restart epoch, strictly above
    assert ctrl.init_world_if_needed(force=True)
    assert ctrl._rendezvous.rank == 0
    assert ctrl._rendezvous.rendezvous_id > glimpsed


def test_flusher_survives_transient_flush_failure(tmp_path, monkeypatch):
    """One failed fdatasync (EIO, ENOSPC, cgroup stall) must not kill
    the flusher thread or lose the buffered events: flush() rewinds
    the partial write, re-queues the blob, and the flusher retries."""
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    real_fdatasync = os.fdatasync
    fails = {"n": 1}

    def flaky_fdatasync(fd):
        if fails["n"]:
            fails["n"] -= 1
            raise OSError("injected EIO")
        return real_fdatasync(fd)

    monkeypatch.setattr(os, "fdatasync", flaky_fdatasync)
    w.append({"ev": "task", "id": 0, "type": int(pb.TRAINING),
              "name": "x", "start": 0, "end": 4, "mv": -1})
    w.kick()
    deadline = time.time() + 10
    state = None
    while time.time() < deadline:
        state = replay_journal(jdir)
        if state is not None and 0 in state.tasks:
            break
        time.sleep(0.2)
    assert state is not None and 0 in state.tasks  # flusher retried
    w.close()
    assert replay_journal(jdir).status == {0: "todo"}  # no duplicates


def test_replay_tolerates_task_record_after_its_lifecycle(tmp_path):
    """Handlers journal outside their locks, so a stalled creator can
    append its 'task' record AFTER another thread journaled the
    dispatch and completion of that very task.  Replay must still
    count the completion instead of silently re-queuing a finished
    task (two-pass apply: creations first)."""
    jdir = str(tmp_path)
    w = JournalWriter(jdir)
    w.append({"ev": "dispatch", "id": 0, "w": 1})
    w.append({"ev": "done", "id": 0})
    w.append({"ev": "task", "id": 0, "type": int(pb.TRAINING),
              "name": "x", "start": 0, "end": 10, "mv": -1})
    w.append({"ev": "task", "id": 1, "type": int(pb.TRAINING),
              "name": "x", "start": 10, "end": 20, "mv": -1})
    w.close()
    state = replay_journal(jdir)
    assert state.status == {0: "done", 1: "todo"}
    assert state.completed_counts[int(pb.TRAINING)] == 1
    assert 0 in state.done_ids  # duplicate re-report still dedups
    assert [t["id"] for t in state.pending_tasks()] == [1]


def test_stale_version_eval_reports_dropped():
    """A straggler completion/metrics report from a finished job
    (tagged with its model_version) must not leak into the next job —
    neither into its creation-window buffers nor into the live job."""
    from elasticdl_tpu.master.evaluation_service import (
        EvaluationService,
    )

    class _CountMetric:
        def __init__(self):
            self.n = 0

        def update(self, outputs, labels):
            self.n += 1

        def result(self):
            return float(self.n)

    tm = TaskManager(
        evaluation_shards=[("e", 0, 10)], records_per_task=10,
    )
    es = EvaluationService(
        tm, lambda: {"n": _CountMetric()}, evaluation_steps=1,
    )
    assert es.add_evaluation_task_if_needed(model_version=1)
    es.report_evaluation_metrics("o", "l", model_version=1)
    es.complete_task(model_version=1)  # job v1 finishes, retires
    assert es.history == [(1, {"n": 1.0})]

    real_create = tm.create_evaluation_tasks

    def create_then_straggle(model_version):
        total = real_create(model_version)
        # straggler v1 duplicates land inside v2's creation window...
        es.report_evaluation_metrics("o", "l", model_version=1)
        es.complete_task(model_version=1)
        # ...alongside a legitimate v2 report racing the assignment
        es.report_evaluation_metrics("o", "l", model_version=2)
        return total

    tm.create_evaluation_tasks = create_then_straggle
    assert es.add_evaluation_task_if_needed(model_version=2)
    tm.create_evaluation_tasks = real_create
    # v1 stragglers dropped; the v2 metric was buffered and folded in
    assert es._job is not None and es._job._completed_tasks == 0
    es.complete_task(model_version=2)
    assert es.history == [(1, {"n": 1.0}), (2, {"n": 1.0})]
    # stale completion against a LIVE job is ignored too
    assert es.add_evaluation_task_if_needed(model_version=3)
    es.complete_task(model_version=2)
    assert es._job is not None and es._job._completed_tasks == 0
    es.complete_task(model_version=3)
    assert [v for v, _ in es.history] == [1, 2, 3]


# -- retry policy ------------------------------------------------------------

def test_retry_policy_rides_transients_and_counts(tmp_path):
    timing = Timing()
    sleeps = []
    policy = RetryPolicy(
        name="t", deadline_secs=60.0, base_delay_secs=0.01,
        timing=timing, sleep=sleeps.append,
    )
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise FakeRpcError()
        return "ok"

    assert policy.call(flaky) == "ok"
    assert calls["n"] == 3
    assert timing.counters()["rpc_retry"] == 2
    assert "rpc_gaveup" not in timing.counters()
    assert len(sleeps) == 2 and sleeps[1] > sleeps[0]


def test_retry_policy_budget_exhaustion_and_nonretryable():
    timing = Timing()
    policy = RetryPolicy(
        name="t2", max_attempts=3, deadline_secs=None,
        base_delay_secs=0.0, timing=timing, sleep=lambda s: None,
    )
    with pytest.raises(grpc.RpcError):
        policy.call(lambda: (_ for _ in ()).throw(FakeRpcError()))
    assert timing.counters()["rpc_gaveup"] == 1
    assert timing.counters()["rpc_retry"] == 2  # 3 attempts, 2 pauses

    # Non-transient errors surface immediately, no retry burned.
    with pytest.raises(ValueError):
        policy.call(lambda: (_ for _ in ()).throw(ValueError("bad")))
    assert timing.counters()["rpc_retry"] == 2


def test_retry_policy_deterministic_jitter():
    d1 = [RetryPolicy(name="x", deadline_secs=1).delay_secs(i)
          for i in range(6)]
    d2 = [RetryPolicy(name="x", deadline_secs=1).delay_secs(i)
          for i in range(6)]
    assert d1 == d2


def test_wait_for_channel_ready_budget_still_raises():
    channel = grpc_utils.build_channel("localhost:1")  # nothing there
    start = time.monotonic()
    with pytest.raises(grpc.FutureTimeoutError):
        grpc_utils.wait_for_channel_ready(
            channel, timeout=0.3, deadline_secs=0.9
        )
    assert 0.5 < time.monotonic() - start < 10.0
    channel.close()


# -- deferred-report outage riding ------------------------------------------

class FlakyMasterClient:
    """get_task feeds fixed shards; report_batch_done fails N times."""

    def __init__(self, sizes, fail_times):
        self._tasks = [
            SimpleNamespace(
                id=i + 1, type=pb.TRAINING,
                shard=SimpleNamespace(name="s", start=0, end=size,
                                      record_indices=[]),
                model_version=-1,
            )
            for i, size in enumerate(sizes)
        ]
        self.fail_times = fail_times
        self.batch_counts = []
        self.results = []

    def get_task(self, task_type=None):
        if self._tasks:
            return self._tasks.pop(0)
        return SimpleNamespace(id=-1, type=pb.TRAINING, shard=None,
                               model_version=-1)

    def report_batch_done(self, count, telemetry=None):
        if self.fail_times > 0:
            self.fail_times -= 1
            raise FakeRpcError()
        self.batch_counts.append(count)

    def report_task_result(self, task_id, err_message="",
                           exec_counters=None, requeue=False):
        self.results.append((task_id, err_message))


def test_failed_flush_rebuffers_and_reflushes_exactly_once():
    mc = FlakyMasterClient([20], fail_times=2)
    svc = DataShardService(mc, batch_size=5)
    svc.fetch_task()
    svc.report_batch_done(5, defer=True)
    svc.flush_batch_done()          # fails -> 5 re-buffered, no raise
    assert mc.batch_counts == []
    svc.report_batch_done(5, defer=True)
    svc.flush_batch_done()          # fails -> 10 buffered
    assert svc._deferred_records == 10
    svc.report_batch_done(5, defer=True)
    svc.flush_batch_done()          # master back: one RPC, 15 records
    assert mc.batch_counts == [15]
    svc.report_batch_done(5, defer=True)  # drains the 20-record shard
    assert mc.batch_counts == [15, 5]
    assert mc.results and mc.results[0][0] == 1
    assert sum(mc.batch_counts) == 20  # nothing lost, nothing doubled


# -- fault injection ---------------------------------------------------------

def test_fault_spec_same_seed_same_schedule():
    text = ("seed=7;report_batch_done:every=3,code=unavailable;"
            "*:prob=0.25,delay_ms=4")
    a = FaultSpec(text).plan("/elasticdl_tpu.Master/report_batch_done", 60)
    b = FaultSpec(text).plan("/elasticdl_tpu.Master/report_batch_done", 60)
    assert a == b
    # The prob clause actually fires sometimes and the schedule is a
    # real mix (not all-on / all-off).
    delayed = [i for i, (d, _) in enumerate(a) if d > 0]
    assert 0 < len(delayed) < 60
    # every=3 clause: abort codes exactly at call 3, 6, 9, ...
    aborted = [i + 1 for i, (_, c) in enumerate(a) if c is not None]
    assert aborted == [i for i in range(1, 61) if i % 3 == 0]
    # A different seed moves the prob coins.
    c = FaultSpec("seed=8;" + text.split(";", 1)[1]).plan(
        "/elasticdl_tpu.Master/report_batch_done", 60
    )
    assert [x[0] for x in c] != [x[0] for x in a]


def test_fault_spec_nth_window_and_blackhole():
    spec = FaultSpec("get_task:nth=2,count=2,blackhole=0.25")
    plan = spec.plan("/elasticdl_tpu.Master/get_task", 5)
    assert [c for _, c in plan] == [
        None, grpc.StatusCode.UNAVAILABLE, grpc.StatusCode.UNAVAILABLE,
        None, None,
    ]
    assert plan[1][0] == pytest.approx(0.25)
    # Methods outside the pattern are untouched.
    assert spec.plan("/elasticdl_tpu.Master/report_version", 3) == [
        (0.0, None)
    ] * 3


def test_fault_spec_down_window_is_wall_clock():
    spec = FaultSpec("*:down=5~10")
    assert spec.decide("/m/x", elapsed_secs=4.9) == (0.0, None)
    assert spec.decide("/m/x", elapsed_secs=5.0) == (
        0.0, grpc.StatusCode.UNAVAILABLE
    )
    assert spec.decide("/m/x", elapsed_secs=10.0) == (0.0, None)


def test_fault_injection_client_rides_every_nth_failure(tmp_path):
    tm = make_tm()
    servicer = MasterServicer(tm)
    server, port = create_master_service(
        servicer,
        interceptors=[FaultInjectionInterceptor(
            "report_batch_done:every=2,code=unavailable"
        )],
    )
    try:
        timing = Timing()
        channel = grpc_utils.build_channel("localhost:%d" % port)
        grpc_utils.wait_for_channel_ready(channel)
        mc = MasterClient(
            channel, worker_id=5,
            retry=RetryPolicy(
                name="test_rpc", deadline_secs=30.0,
                base_delay_secs=0.01, timing=timing,
            ),
        )
        for _ in range(4):
            mc.report_batch_done(10)
        # Server-side calls 2, 4, 6 were aborted; every client call
        # still landed exactly once.
        assert servicer.worker_record_counts[5] == 40
        assert timing.counters()["rpc_retry"] == 3
        assert "rpc_gaveup" not in timing.counters()
    finally:
        server.stop(grace=0)


# -- end-to-end restart over real gRPC --------------------------------------

def test_master_restart_with_outage_riding_client(tmp_path):
    """The drill in miniature: a client mid-job rides a master restart
    on the SAME port; the job finishes with exact accounting."""
    jdir = str(tmp_path)
    port = grpc_utils.find_free_port()
    tm1 = make_tm(journal_dir=jdir)
    server1, _ = create_master_service(
        MasterServicer(tm1, journal=tm1._journal), port=port
    )
    channel = grpc_utils.build_channel("localhost:%d" % port)
    grpc_utils.wait_for_channel_ready(channel)
    timing = Timing()
    mc = MasterClient(
        channel, worker_id=0,
        retry=RetryPolicy(name="e2e", deadline_secs=30.0,
                          base_delay_secs=0.05, timing=timing),
    )
    t1 = mc.get_task()
    mc.report_task_result(t1.id)
    mc.report_batch_done(30)
    t2 = mc.get_task()  # in flight across the crash

    server1.stop(grace=0)  # SIGKILL stand-in
    tm1._journal.close()

    # The worker keeps reporting into the outage on another thread.
    done = threading.Event()

    def report_through_outage():
        mc.report_batch_done(30)
        mc.report_task_result(t2.id)
        done.set()

    reporter = threading.Thread(target=report_through_outage,
                                daemon=True)
    reporter.start()
    time.sleep(0.3)  # let retries begin against the dead port

    tm2, state = restart_tm(jdir)
    servicer2 = MasterServicer(tm2)
    servicer2.restore_from_journal(state)
    server2, _ = create_master_service(servicer2, port=port)
    try:
        assert done.wait(timeout=20.0)
        assert timing.counters().get("rpc_retry", 0) >= 1
        # Finish the job through the restarted master.
        while True:
            t = mc.get_task()
            if t.id < 0:
                break
            mc.report_task_result(t.id)
        counts = tm2.counts()
        assert counts["completed"][pb.TRAINING] == 4
        assert counts["failed"][pb.TRAINING] == 0
        assert tm2.finished()
        # Progress counts rode the restart too.
        assert servicer2.worker_record_counts[0] == 60
    finally:
        server2.stop(grace=0)
        tm2._journal.close()


def test_journal_meta_mismatch_refused(tmp_path):
    from elasticdl_tpu.master.main import _check_journal_meta
    from elasticdl_tpu.master.journal import JournalState

    state = JournalState()
    state.meta = {"num_epochs": 2, "records_per_task": 30}
    with pytest.raises(RuntimeError):
        _check_journal_meta(
            state, {"num_epochs": 3, "records_per_task": 30}
        )
    _check_journal_meta(
        state, {"num_epochs": 2, "records_per_task": 30}
    )
