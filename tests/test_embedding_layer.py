"""Embedding layer API + recio dataset converters end-to-end."""

import numpy as np
import pytest

from elasticdl_tpu.data.reader import RecioDataReader
from elasticdl_tpu.data.recio_gen import (
    convert_synthetic_mnist,
    decode_xy,
)
from elasticdl_tpu.models.embedding import (
    Embedding,
    embedding_feature_column,
)


def test_embedding_sequence_output():
    layer = Embedding("t", dim=4)
    feats = {}
    layer.collect_ids(feats, np.array([[1, 2], [3, 3]]))
    assert feats["__ids__"]["t"].dtype == np.int64
    rows = np.arange(20, dtype=np.float32).reshape(5, 4)
    out = layer({
        "emb__t": rows,
        "idx__t": np.array([[1, 2], [3, 3]], np.int32),
    })
    assert np.asarray(out).shape == (2, 2, 4)
    np.testing.assert_array_equal(np.asarray(out)[0, 0], rows[1])


@pytest.mark.parametrize("combiner", ["sum", "mean", "sqrtn"])
def test_embedding_combiners_with_mask(combiner):
    layer = Embedding("t", dim=2, combiner=combiner)
    rows = np.array([[1.0, 1.0], [3.0, 3.0]], np.float32)
    idx = np.array([[0, 1, 1]], np.int32)
    mask = np.array([[1.0, 1.0, 0.0]], np.float32)  # last id padded out
    out = np.asarray(layer({
        "emb__t": rows, "idx__t": idx, "mask__t": mask
    }))
    expect = {"sum": 4.0, "mean": 2.0, "sqrtn": 4.0 / np.sqrt(2)}
    np.testing.assert_allclose(out[0, 0], expect[combiner], rtol=1e-6)


def test_feature_column_helper():
    col = embedding_feature_column("age_bucket", vocab_size=11, dim=3)
    assert col.name == "col__age_bucket"
    assert col.vocab_size == 11
    assert col.info["dim"] == 3


def test_recio_gen_roundtrip_through_reader(tmp_path):
    files = convert_synthetic_mnist(str(tmp_path), n=100,
                                    records_per_file=40)
    assert len(files) == 3
    reader = RecioDataReader(str(tmp_path), decode_fn=decode_xy)
    shards = reader.create_shards()
    assert sum(end - start for _, start, end in shards) == 100

    from elasticdl_tpu.master.task_manager import TaskManager

    tm = TaskManager(training_shards=shards, records_per_task=40)
    count = 0
    while True:
        task = tm.get(0)
        if task is None:
            break
        for x, y in reader.read_records(task):
            assert x.shape == (28, 28)
            count += 1
        tm.report(task.id, True)
    assert count == 100
