"""k8s backend verified without a cluster (VERDICT r1 #9).

Golden manifests for the client renderer, and a fake CoreV1Api driving
K8sWorkerBackend's launch/wait/relaunch surface — including the
reference behaviors: service-per-worker patched onto the replacement
pod on relaunch (common/k8s_client.py:261-274), high/low pod priority
split (pod_manager.py:80-99), and the cluster-spec patch hooks
(elasticdl_client/common/k8s_client.py:106-218).
"""

import sys
import threading
import types

from elasticdl_tpu.client.k8s_renderer import parse_resource_string
from elasticdl_tpu.client.k8s_submit import render_manifests
from elasticdl_tpu.master.k8s_backend import K8sWorkerBackend
from elasticdl_tpu.master.worker_manager import WorkerManager


class FakePod:
    def __init__(self, manifest):
        self.manifest = manifest
        self.phase = "Running"
        self.exit_code = None

    def as_dict(self):
        status = {"phase": self.phase}
        if self.exit_code is not None:
            status["containerStatuses"] = [
                {"state": {"terminated": {"exitCode": self.exit_code}}}
            ]
        return dict(self.manifest, status=status)


class FakeCoreV1Api:
    """Record-and-replay stand-in for kubernetes.client.CoreV1Api."""

    def __init__(self):
        self.pods = {}       # name -> FakePod
        self.services = {}   # name -> manifest
        self.patches = []    # (service_name, body)

    def create_namespaced_pod(self, namespace, body):
        self.pods[body["metadata"]["name"]] = FakePod(body)

    def read_namespaced_pod(self, name, namespace):
        if name not in self.pods:
            raise KeyError(name)
        return self.pods[name].as_dict()

    def delete_namespaced_pod(self, name, namespace,
                              grace_period_seconds=None):
        self.pods.pop(name, None)

    def create_namespaced_service(self, namespace, body):
        self.services[body["metadata"]["name"]] = body

    def patch_namespaced_service(self, name, namespace, body):
        if name not in self.services:
            raise KeyError(name)
        self.services[name] = body
        self.patches.append((name, body))


def make_backend(**kwargs):
    api = FakeCoreV1Api()
    backend = K8sWorkerBackend(
        "job", "image:tag", core_api=api, poll_secs=0.05,
        worker_args=["--model_zoo", "mnist"], **kwargs,
    )
    return api, backend


# -- manifests ----------------------------------------------------------------

def test_pod_manifest_golden():
    _, backend = make_backend(resources={"cpu": "4"},
                              tpu_topology="2x2")
    pod = backend.pod_manifest(3, "master:50001")
    assert pod == {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": "job-worker-3",
            "labels": {
                "elasticdl-tpu-job-name": "job",
                "replica-type": "worker",
                "replica-index": "3",
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": "worker",
                "image": "image:tag",
                "command": ["python", "-m", "elasticdl_tpu.worker.main"],
                "args": ["--model_zoo", "mnist"],
                "env": [
                    {"name": "MASTER_ADDR", "value": "master:50001"},
                    {"name": "WORKER_ID", "value": "3"},
                ],
                "resources": {"requests": {"cpu": "4"}},
            }],
            "nodeSelector": {
                "cloud.google.com/gke-tpu-topology": "2x2"
            },
        },
    }


def test_master_manifest_golden_and_resources():
    text = render_manifests(
        ["--job_name", "myjob", "--num_workers", "2"], "img:1",
        namespace="ml",
    )
    assert '"name": "myjob-master"' in text
    assert '"namespace": "ml"' in text
    assert '"master"' in text
    assert '"--num_workers"' in text and '"2"' in text
    assert '"kind": "Service"' in text  # master service alongside
    assert parse_resource_string("cpu=1,memory=4Gi,google.com/tpu=8") == {
        "cpu": "1", "memory": "4Gi", "google.com/tpu": "8",
    }


def test_priority_split():
    """First ceil(fraction*num_workers) workers get the high class."""
    _, backend = make_backend(num_workers=4, high_priority_fraction=0.5,
                              priority_class_high="hi",
                              priority_class_low="lo")
    classes = [
        backend.pod_manifest(i, "m:1")["spec"].get("priorityClassName")
        for i in range(4)
    ]
    assert classes == ["hi", "hi", "lo", "lo"]


def test_cluster_spec_hooks_patch_manifests():
    mod = types.ModuleType("fake_cluster_spec")

    def patch_pod(manifest):
        manifest["spec"]["tolerations"] = [{"key": "tpu"}]
        return manifest

    def patch_service(manifest):
        manifest["metadata"]["labels"]["site"] = "dc-7"
        return manifest

    mod.patch_pod = patch_pod
    mod.patch_service = patch_service
    sys.modules["fake_cluster_spec"] = mod
    try:
        _, backend = make_backend(cluster_spec="fake_cluster_spec")
        pod = backend.pod_manifest(0, "m:1")
        svc = backend.service_manifest(0)
        assert pod["spec"]["tolerations"] == [{"key": "tpu"}]
        assert svc["metadata"]["labels"]["site"] == "dc-7"
    finally:
        del sys.modules["fake_cluster_spec"]


# -- backend lifecycle against the fake API -----------------------------------

def test_launch_creates_pod_and_service():
    api, backend = make_backend()
    ref = backend.launch(0, "m:1")
    assert ref == "job-worker-0"
    assert "job-worker-0" in api.pods
    assert "job-worker-0" in api.services
    sel = api.services["job-worker-0"]["spec"]["selector"]
    assert sel["replica-index"] == "0"


def test_wait_maps_phases_to_exit_codes():
    api, backend = make_backend()
    for phase, exit_code, want in (
        ("Succeeded", None, 0),
        ("Failed", 1, 1),
        ("Failed", 137, 137),   # OOMKilled -> no relaunch upstream
    ):
        ref = backend.launch(9, "m:1")
        api.pods[ref].phase = phase
        api.pods[ref].exit_code = exit_code
        done = {}

        def run():
            done["code"] = backend.wait(ref)

        t = threading.Thread(target=run)
        t.start()
        t.join(timeout=10)
        assert done["code"] == want, (phase, exit_code, done)


def test_wait_reports_deleted_pod_as_preemption():
    api, backend = make_backend()
    ref = backend.launch(1, "m:1")
    backend.kill(ref, force=True)  # pod gone from the API
    assert backend.wait(ref) == -9
    assert not backend.is_alive(ref)


def test_relaunch_patches_service_to_replacement():
    """The reference's service continuity: worker 0 dies, worker 1
    replaces it, and slot 0's service now selects worker 1's pod."""
    api, backend = make_backend()
    backend.launch(0, "m:1")
    backend.launch(1, "m:1", slot=0)
    assert len(api.patches) == 1
    name, body = api.patches[0]
    assert name == "job-worker-0"
    assert body["spec"]["selector"]["replica-index"] == "1"
    # no second service created for the replacement
    assert "job-worker-1" not in api.services


def test_second_relaunch_keeps_slot_service_chain():
    """Worker 1 (already a replacement for slot 0) dies and worker 2
    replaces it: slot 0's service must select worker 2 (review r2: the
    predecessor-id chain broke here, patching a nonexistent service)."""
    api, backend = make_backend()
    backend.launch(0, "m:1")
    backend.launch(1, "m:1", slot=0)
    backend.launch(2, "m:1", slot=0)
    sel = api.services["job-worker-0"]["spec"]["selector"]
    assert sel["replica-index"] == "2"
    assert "job-worker-1" not in api.services
    assert "job-worker-2" not in api.services


def test_patch_missing_service_self_heals():
    api, backend = make_backend()
    backend.launch(0, "m:1")
    del api.services["job-worker-0"]  # deleted externally
    backend.launch(1, "m:1", slot=0)
    # self-healed: recreated, selecting the replacement
    sel = api.services["job-worker-0"]["spec"]["selector"]
    assert sel["replica-index"] == "1"


def test_relaunched_high_priority_slot_keeps_protection():
    """Priority follows the slot: the replacement for a high-priority
    worker stays high (review r2: the protected core eroded)."""
    _, backend = make_backend(num_workers=4, high_priority_fraction=0.5,
                              priority_class_high="hi",
                              priority_class_low="lo")
    pod = backend.pod_manifest(7, "m:1", slot=0)  # replacement for slot 0
    assert pod["spec"]["priorityClassName"] == "hi"
    pod = backend.pod_manifest(8, "m:1", slot=3)
    assert pod["spec"]["priorityClassName"] == "lo"


def test_pod_manifest_carries_extra_env_and_slot_addresses():
    """The foreign-runtime cluster-spec hook on the k8s backend: the
    TF_CONFIG (or any) extra env rides the pod manifest, and
    slot_addresses() yields the stable per-slot service DNS names to
    build it from (reference pod_manager.py:405-422)."""
    _, backend = make_backend()
    addrs = backend.slot_addresses(2)
    assert addrs == ["job-worker-0.default.svc:50002",
                     "job-worker-1.default.svc:50002"]
    pod = backend.pod_manifest(
        1, "m:1", extra_env={"TF_CONFIG": '{"task": 1}'})
    env = {e["name"]: e["value"]
           for e in pod["spec"]["containers"][0]["env"]}
    assert env["TF_CONFIG"] == '{"task": 1}'
    assert env["WORKER_ID"] == "1"


def test_volume_string_mounts_on_worker_pods():
    """--volume (reference elasticdl_client/common/k8s_volume.py): PVC
    and hostPath entries become pod volumes + container mounts; a
    repeated claim reuses ONE volume with two mounts."""
    from elasticdl_tpu.client.k8s_renderer import parse_volume_string

    volumes, mounts = parse_volume_string(
        "claim_name=data,mount_path=/data;"
        "claim_name=data,mount_path=/data2,sub_path=sub,read_only=true;"
        "host_path=/mnt/ssd,mount_path=/ssd"
    )
    assert [v["name"] for v in volumes] == [
        "pvc-data-f363", "hostpath-mnt-ssd-4c86"]
    assert volumes[0]["persistentVolumeClaim"]["claimName"] == "data"
    assert volumes[1]["hostPath"]["path"] == "/mnt/ssd"
    assert mounts[1] == {"name": "pvc-data-f363", "mountPath": "/data2",
                         "subPath": "sub", "readOnly": True}
    # Near-identical sources must NOT collapse to one volume name.
    vols2, _ = parse_volume_string(
        "claim_name=data.x,mount_path=/a;claim_name=data-x,mount_path=/b")
    assert len({v["name"] for v in vols2}) == 2

    import pytest as _pytest

    with _pytest.raises(ValueError):
        parse_volume_string("claim_name=c")  # no mount_path
    with _pytest.raises(ValueError):
        parse_volume_string("mount_path=/p")  # no source

    _, backend = make_backend(volume="claim_name=data,mount_path=/data")
    pod = backend.pod_manifest(0, "m:1")
    assert pod["spec"]["volumes"][0]["name"] == "pvc-data-f363"
    assert (pod["spec"]["containers"][0]["volumeMounts"][0]["mountPath"]
            == "/data")


def test_worker_manager_drives_k8s_relaunch_end_to_end():
    """WorkerManager + K8sWorkerBackend against the fake API: preempt a
    pod (delete it), watch the DELETED -> relaunch flow create a fresh
    pod and patch the dead slot's service onto it."""
    api = FakeCoreV1Api()
    backend = K8sWorkerBackend("job", "img", core_api=api,
                               poll_secs=0.05)
    mgr = WorkerManager(backend, num_workers=1)
    mgr.set_master_addr("m:1")
    mgr.start()
    assert "job-worker-0" in api.pods
    # preempt: delete the pod out from under the watcher
    api.delete_namespaced_pod("job-worker-0", "default")
    deadline = threading.Event()
    for _ in range(100):
        if "job-worker-1" in api.pods:
            break
        deadline.wait(0.1)
    assert "job-worker-1" in api.pods, "no relaunch pod appeared"
    assert api.patches and api.patches[0][0] == "job-worker-0"
    assert (
        api.patches[0][1]["spec"]["selector"]["replica-index"] == "1"
    )
    # the relaunched pod carries slot 0's replica semantics end to end
    assert "job-worker-1" not in api.services
    mgr.stop()


# -- client submission path (VERDICT r2 #5) ----------------------------------

def test_submit_job_creates_master_pod_and_service():
    from elasticdl_tpu.client import k8s_submit

    api = FakeCoreV1Api()
    argv = ["--job_type", "train", "--job_name", "myjob",
            "--model_zoo", "mnist"]
    name = k8s_submit.submit_job(
        argv, image="img:1", namespace="ns", core_api=api,
        resources={"cpu": "2"},
    )
    assert name == "myjob-master"
    pod = api.pods["myjob-master"].manifest
    assert pod["metadata"]["labels"] == {
        "elasticdl-tpu-job-name": "myjob",
        "replica-type": "master",
        "replica-index": "0",
    }
    assert pod["metadata"]["namespace"] == "ns"
    c = pod["spec"]["containers"][0]
    assert c["command"] == ["python", "-m", "elasticdl_tpu.master.main"]
    assert c["args"] == argv
    assert c["resources"]["requests"] == {"cpu": "2"}
    # downward-API identity for worker ownerReferences
    fields = {
        e["name"]: e["valueFrom"]["fieldRef"]["fieldPath"]
        for e in c["env"] if "valueFrom" in e
    }
    assert fields["POD_NAME"] == "metadata.name"
    assert fields["POD_UID"] == "metadata.uid"
    svc = api.services["myjob-master"]
    assert svc["spec"]["selector"]["replica-type"] == "master"
    assert svc["spec"]["ports"][0]["port"] == 50001


def test_submit_job_applies_cluster_spec_hooks():
    from elasticdl_tpu.client import k8s_submit

    mod = types.ModuleType("fake_submit_spec")
    mod.patch_pod = lambda m: (
        m["spec"].__setitem__("nodeSelector", {"pool": "tpu"}) or m
    )
    sys.modules["fake_submit_spec"] = mod
    try:
        api = FakeCoreV1Api()
        k8s_submit.submit_job(
            ["--job_name", "j2"], image="img", core_api=api,
            cluster_spec="fake_submit_spec",
        )
        pod = api.pods["j2-master"].manifest
        assert pod["spec"]["nodeSelector"] == {"pool": "tpu"}
    finally:
        del sys.modules["fake_submit_spec"]


def test_cli_k8s_platform_submits_via_api():
    from elasticdl_tpu.client.main import _run_job

    api = FakeCoreV1Api()
    rc = _run_job(
        "train",
        ["--platform", "k8s", "--image", "img:2",
         "--namespace", "prod", "--job_name", "cli-job",
         "--model_zoo", "mnist",
         "--master_resource_request", "cpu=3,memory=1Gi"],
        core_api=api,
    )
    assert rc == 0
    pod = api.pods["cli-job-master"].manifest
    assert pod["spec"]["containers"][0]["image"] == "img:2"
    assert pod["metadata"]["namespace"] == "prod"
    assert pod["spec"]["containers"][0]["resources"]["requests"] == {
        "cpu": "3", "memory": "1Gi",
    }
    # --job_type was prepended for the master
    args = pod["spec"]["containers"][0]["args"]
    assert args[:2] == ["--job_type", "train"]
    # a cluster submission defaults the master to k8s worker PODS —
    # without this the workers run as subprocesses inside the master
    # pod (ADVICE r3 medium)
    assert args[args.index("--worker_backend") + 1] == "k8s"


def test_cli_k8s_explicit_worker_backend_wins():
    from elasticdl_tpu.client.main import _run_job

    api = FakeCoreV1Api()
    rc = _run_job(
        "train",
        ["--platform", "k8s", "--job_name", "pj",
         "--model_zoo", "mnist", "--worker_backend", "process"],
        core_api=api,
    )
    assert rc == 0
    args = api.pods["pj-master"].manifest["spec"]["containers"][0]["args"]
    assert args.count("--worker_backend") == 1
    assert args[args.index("--worker_backend") + 1] == "process"


def test_cli_k8s_output_renders_manifest(tmp_path):
    import json as _json

    from elasticdl_tpu.client.main import _run_job

    out = tmp_path / "job.yaml"
    rc = _run_job(
        "train",
        ["--platform", "k8s", "--job_name", "rjob",
         "--output", str(out), "--model_zoo", "mnist"],
    )
    assert rc == 0
    docs = out.read_text().split("---\n")
    pod = _json.loads(docs[0])
    svc = _json.loads(docs[1])
    assert pod["metadata"]["name"] == "rjob-master"
    assert svc["kind"] == "Service"


def test_worker_pods_carry_owner_reference():
    api = FakeCoreV1Api()
    backend = K8sWorkerBackend(
        "job", "image:tag", core_api=api, poll_secs=0.05,
        worker_args=[], owner_ref={"name": "job-master", "uid": "u-123"},
    )
    backend.launch(0, "master:50001")
    pod = api.pods["job-worker-0"].manifest
    ref = pod["metadata"]["ownerReferences"][0]
    assert ref["name"] == "job-master"
    assert ref["uid"] == "u-123"
    assert ref["controller"] is True
    svc = api.services["job-worker-0"]
    assert svc["metadata"]["ownerReferences"][0]["uid"] == "u-123"


def test_owner_ref_from_env():
    from elasticdl_tpu.master.k8s_backend import owner_ref_from_env

    assert owner_ref_from_env({}) is None
    assert owner_ref_from_env(
        {"POD_NAME": "m", "POD_UID": "u"}
    ) == {"name": "m", "uid": "u"}


def test_master_builds_k8s_backend_from_flags(monkeypatch):
    from elasticdl_tpu.master.main import _build_worker_backend
    from elasticdl_tpu.utils.args import parse_master_args

    monkeypatch.setenv("POD_NAME", "job-master")
    monkeypatch.setenv("POD_UID", "u-9")
    args = parse_master_args([
        "--worker_backend", "k8s", "--image", "w:1",
        "--namespace", "ns", "--num_workers", "4",
        "--worker_resource_request", "cpu=1",
        "--worker_pod_priority", "0.5",
    ])
    backend = _build_worker_backend(args, ["--model_zoo", "mnist"])
    assert isinstance(backend, K8sWorkerBackend)
    backend._core = FakeCoreV1Api()
    backend.launch(0, "m:1")
    pod = backend._core.pods["elasticdl-tpu-job-worker-0"].manifest
    assert pod["spec"]["containers"][0]["image"] == "w:1"
    assert pod["metadata"]["ownerReferences"][0]["uid"] == "u-9"
    # first ceil(0.5*4)=2 slots ride the high priority class
    assert pod["spec"]["priorityClassName"] == "high-priority"
