"""JobMonitor probes a live master without disturbing the job
(reference k8s_job_monitor.py:32-100 probe-and-summarize role)."""

from elasticdl_tpu.master.job_monitor import JobMonitor
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.task_manager import TaskManager


def test_snapshot_against_live_master():
    rendezvous = RendezvousServer(grace_secs=0.0)
    task_manager = TaskManager(
        training_shards=[("x", 0, 40)], records_per_task=10
    )
    master = Master(task_manager, rendezvous_server=rendezvous)
    master.prepare()
    try:
        monitor = JobMonitor("localhost:%d" % master.port, poll_secs=0)
        snap = monitor.snapshot()
        assert snap["reachable"]
        assert snap["world_size"] == 0  # nobody joined yet
        assert snap["dispatching"]     # WAIT or real work on offer
        # the probe must not consume real work: all 4 tasks remain
        counts = task_manager.counts()
        assert counts["todo"] + counts["doing"] == 4
        assert all(v == 0 for v in counts["failed"].values())
    finally:
        master.stop()


def test_snapshot_reports_unreachable():
    monitor = JobMonitor("localhost:1", poll_secs=0)
    snap = monitor.snapshot()
    assert not snap["reachable"]
    assert "error" in snap


def test_probe_never_burns_retries():
    """A monitor that happens to receive a real task must requeue it
    without consuming a retry — repeated probes must not permanently
    fail work."""
    task_manager = TaskManager(
        evaluation_shards=[("e", 0, 10)], records_per_task=10,
    )
    master = Master(task_manager)
    master.prepare()
    task_manager.create_evaluation_tasks(model_version=1)
    try:
        monitor = JobMonitor("localhost:%d" % master.port, poll_secs=0)
        for _ in range(10):  # way past max_task_retries
            monitor.snapshot()
        counts = task_manager.counts()
        assert all(v == 0 for v in counts["failed"].values()), counts
        assert counts["todo"] + counts["doing"] == 1  # task intact
        # retry budget untouched
        task = task_manager.get(0)
        assert task is not None and task.retry_count == 0
    finally:
        master.stop()


def test_probe_does_not_complete_eval_jobs():
    """A requeued eval task must not count toward evaluation-job
    completion (only real results do)."""
    from elasticdl_tpu.master.evaluation_service import EvaluationService

    task_manager = TaskManager(
        evaluation_shards=[("e", 0, 10)], records_per_task=10,
    )
    eval_service = EvaluationService(task_manager, lambda: {},
                                     evaluation_steps=1)
    master = Master(task_manager, evaluation_service=eval_service)
    master.prepare()
    try:
        eval_service.add_evaluation_task_if_needed(model_version=1)
        monitor = JobMonitor("localhost:%d" % master.port, poll_secs=0)
        monitor.snapshot()  # peeks + requeues the eval task
        job = eval_service._job
        assert job is not None and job._completed_tasks == 0
    finally:
        master.stop()


def test_completion_in_next_creation_window_buffers(monkeypatch):
    """A completion landing inside job #2's creation window must be
    buffered and folded into job #2 — not applied to the retired,
    already-finished job #1, which would wedge job #2 one completion
    short forever and block every later evaluation."""
    from elasticdl_tpu.master.evaluation_service import EvaluationService

    task_manager = TaskManager(
        evaluation_shards=[("e", 0, 10)], records_per_task=10,
    )
    eval_service = EvaluationService(task_manager, lambda: {},
                                     evaluation_steps=1)
    assert eval_service.add_evaluation_task_if_needed(model_version=1)
    eval_service.complete_task()  # the single task: job #1 finishes
    assert eval_service._job is None  # retired, not left in place
    assert [v for v, _ in eval_service.history] == [1]

    real_create = task_manager.create_evaluation_tasks

    def create_then_race(model_version):
        total = real_create(model_version)
        # a fast worker finishes a task before _job is assigned
        eval_service.complete_task()
        return total

    monkeypatch.setattr(
        task_manager, "create_evaluation_tasks", create_then_race
    )
    assert eval_service.add_evaluation_task_if_needed(model_version=2)
    # the raced completion reached job #2 (one task => finished), so
    # history gained exactly one entry and evaluation #3 is not wedged
    assert [v for v, _ in eval_service.history] == [1, 2]
    assert eval_service.add_evaluation_task_if_needed(model_version=3)
