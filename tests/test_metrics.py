import pytest
import numpy as np

from elasticdl_tpu.utils import metrics


def test_accuracy():
    m = metrics.Accuracy()
    m.update(np.array([[0.9, 0.1], [0.1, 0.9]]), np.array([0, 0]))
    assert abs(m.result() - 0.5) < 1e-9


def test_binary_accuracy():
    m = metrics.BinaryAccuracy()
    m.update(np.array([0.9, 0.2, 0.7]), np.array([1, 0, 0]))
    assert abs(m.result() - 2 / 3) < 1e-9


def test_mse_streams():
    m = metrics.MeanSquaredError()
    m.update(np.array([1.0, 2.0]), np.array([0.0, 0.0]))
    m.update(np.array([3.0]), np.array([0.0]))
    assert abs(m.result() - (1 + 4 + 9) / 3) < 1e-9


def test_auc_perfect_and_random():
    m = metrics.AUC()
    scores = np.concatenate([np.random.rand(500) * 0.4,
                             0.6 + np.random.rand(500) * 0.4])
    labels = np.concatenate([np.zeros(500), np.ones(500)])
    m.update(scores, labels)
    assert m.result() > 0.99
    m2 = metrics.AUC()
    rng = np.random.RandomState(0)
    m2.update(rng.rand(4000), rng.randint(0, 2, 4000))
    assert 0.45 < m2.result() < 0.55


def test_precision_recall_topk_mae():
    from elasticdl_tpu.utils.metrics import (
        MeanAbsoluteError,
        Precision,
        Recall,
        TopKAccuracy,
    )

    p, r = Precision(), Recall()
    scores = np.array([0.9, 0.8, 0.2, 0.6])
    labels = np.array([1, 0, 1, 1])
    for m in (p, r):
        m.update(scores[:2], labels[:2])  # streaming in two chunks
        m.update(scores[2:], labels[2:])
    assert p.result() == pytest.approx(2 / 3)   # TP=2 FP=1
    assert r.result() == pytest.approx(2 / 3)   # TP=2 FN=1

    topk = TopKAccuracy(k=2)
    logits = np.array([[0.1, 0.5, 0.4], [0.7, 0.2, 0.1]])
    topk.update(logits, np.array([2, 2]))  # in top-2 / not in top-2
    assert topk.result() == pytest.approx(0.5)

    mae = MeanAbsoluteError()
    mae.update(np.array([1.0, 3.0]), np.array([2.0, 1.0]))
    assert mae.result() == pytest.approx(1.5)
