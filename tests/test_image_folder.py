"""Image-folder plumbing: reader, elastic dataset, recio packing
(reference ElasticImageFolder + image recordio_gen)."""

import numpy as np
import pytest

from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.data.image_folder import (
    ImageFolderDataReader,
    pack_image_folder,
    scan_image_folder,
)
from elasticdl_tpu.master.task_manager import Shard, Task


@pytest.fixture(scope="module")
def folder(tmp_path_factory):
    from PIL import Image

    root = tmp_path_factory.mktemp("images")
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        (root / cls).mkdir()
        for i in range(6):
            arr = (rng.rand(10, 12, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(root / cls / ("%d.png" % i))
    return str(root)


def test_scan_sorted_and_labeled(folder):
    samples, classes = scan_image_folder(folder)
    assert classes == ["cat", "dog"]
    assert len(samples) == 12
    assert {label for _, label in samples} == {0, 1}


def test_reader_decodes_resized_float(folder):
    reader = ImageFolderDataReader(folder, image_size=8,
                                   records_per_shard=5)
    assert reader.get_size() == 12 and reader.num_classes() == 2
    shards = reader.create_shards()
    assert [s[1:] for s in shards] == [(0, 5), (5, 10), (10, 12)]
    records = list(
        reader.read_records(Task(0, Shard(folder, 0, 5), 0))
    )
    assert len(records) == 5
    x, y = records[0]
    assert x.shape == (8, 8, 3) and x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() <= 1.0 and y == 0
    # shuffled indices honored
    got = [
        y for _, y in reader.read_records(
            Task(0, Shard(folder, 0, 12, record_indices=[11, 0]), 0)
        )
    ]
    assert got == [1, 0]


def test_factory_origin(folder):
    reader = create_data_reader("imagefolder:%s:16" % folder,
                                records_per_shard=4)
    x, y = next(iter(
        reader.read_records(Task(0, Shard(folder, 0, 1), 0))
    ))
    assert x.shape == (16, 16, 3)


def test_pack_image_folder_roundtrip(folder, tmp_path):
    from elasticdl_tpu.data.reader import RecioDataReader
    from elasticdl_tpu.data.recio_gen import decode_xy

    out = str(tmp_path / "packed")
    count, classes = pack_image_folder(folder, out, image_size=8,
                                       records_per_file=5)
    assert count == 12 and classes == ["cat", "dog"]
    reader = RecioDataReader(out, decode_fn=decode_xy)
    shards = reader.create_shards()
    total = sum(end - start for _, start, end in shards)
    assert total == 12
    name, start, end = shards[0]
    x, y = next(iter(
        reader.read_records(Task(0, Shard(name, start, start + 1), 0))
    ))
    assert x.shape == (8, 8, 3) and x.dtype == np.float32


def test_elastic_image_folder_consumes_master_indices(folder):
    """__getitem__ ignores the sampler and pulls dynamic indices."""
    from elasticdl_tpu.data.image_folder import ElasticImageFolder

    class FakeMC:
        def __init__(self):
            self._indices = [3, 7]
            self._done = False

        def get_task(self, task_type=None):
            from types import SimpleNamespace

            from elasticdl_tpu.proto import elastic_pb2 as pb

            if self._done:
                return SimpleNamespace(id=-1, type=pb.NONE, shard=None,
                                       model_version=-1)
            self._done = True
            return SimpleNamespace(
                id=0, type=pb.TRAINING,
                shard=SimpleNamespace(name="x", start=0, end=2,
                                      record_indices=[3, 7]),
                model_version=-1,
            )

        def report_batch_done(self, count, telemetry=None):
            pass

        def report_task_result(self, *a, **k):
            pass

    ds = ElasticImageFolder(folder, FakeMC(), image_size=8)
    x0, y0 = ds[999]  # sampler index ignored
    x1, y1 = ds[0]
    assert x0.shape == (8, 8, 3)
    samples, _ = scan_image_folder(folder)
    assert (y0, y1) == (samples[3][1], samples[7][1])
    ds.stop()


def test_augmentation_preserves_shape_and_varies(folder):
    """augment: random crop + flip on the HOST — output shape is the
    jitted step's static shape, repeated reads differ, and the factory
    origin's :augment option (only) enables it."""
    from elasticdl_tpu.data.factory import create_data_reader
    from elasticdl_tpu.data.image_folder import augment_image

    rng = np.random.RandomState(0)
    img = np.arange(8 * 8 * 3, dtype=np.float32).reshape(8, 8, 3)
    outs = [augment_image(img, rng) for _ in range(8)]
    assert all(o.shape == img.shape for o in outs)
    assert any(not np.array_equal(outs[0], o) for o in outs[1:])

    plain = create_data_reader("imagefolder:%s:16" % folder)
    aug = create_data_reader("imagefolder:%s:16:augment" % folder)
    # All 12 records, not 2: the reader draws OS entropy by design, and
    # a center-crop + no-flip draw leaves one image unperturbed with
    # p≈1/50 — over 2 records the "something changed" assertion flaked
    # about once in 2.5k suite runs; over 12 it cannot.
    task = Task(0, Shard(folder, 0, 12), 0)
    a = [r[0] for r in plain.read_records(task)]
    b = [r[0] for r in aug.read_records(task)]
    assert a[0].shape == b[0].shape == (16, 16, 3)
    assert not all(
        np.array_equal(x, y) for x, y in zip(a, b)
    )  # augmentation actually perturbed something
    with pytest.raises(ValueError, match="augment"):
        create_data_reader("imagefolder:%s:16:flip" % folder)
    with pytest.raises(ValueError, match="augment"):
        create_data_reader("imagefolder:%s:16:augment:noflip" % folder)

    # eval/predict tasks through the SAME augmented reader get raw
    # images (deterministic metrics)
    eval_task = Task(0, Shard(folder, 0, 2), 1)  # EVALUATION
    raw = [r[0] for r in plain.read_records(task)]
    ev = [r[0] for r in aug.read_records(eval_task)]
    for x, y in zip(raw, ev):
        np.testing.assert_array_equal(x, y)
