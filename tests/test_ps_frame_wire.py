"""Frame-native PS data plane over real in-process gRPC (PR 17).

What the pb wire tests in test_pserver.py prove for TensorPB, this
file proves for the raw-frame RPCs: negotiation (auto-upgrade on the
``frame_capable`` bit, rolling downgrade on UNIMPLEMENTED), apply
bit-identity frame-vs-pb at the same seed, generation fencing read
from the frame HEADER (rejected before any payload decode), and the
hostile-blob contract — every malformed frame class must come back a
loud INVALID_ARGUMENT with the servicer intact on the same
connection."""

import grpc
import numpy as np
import pytest

from elasticdl_tpu.proto import rpc
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.ps.optimizer import create_optimizer
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.utils import grpc_utils, tensor_codec
from elasticdl_tpu.worker.ps_client import PSClient


def start_ps(num_ps=1, opt_type="sgd", opt_args="learning_rate=0.1",
             frame_wire="auto", legacy_wire=False, **kwargs):
    """Boot N in-process PS shards; returns (client, servicers,
    servers).  ``legacy_wire=True`` registers every method EXCEPT the
    frame RPCs — the pre-frame server binary a rolling downgrade must
    survive (its legacy pull still advertises ``frame_capable``, which
    is exactly the trap: the client upgrades, then hits
    UNIMPLEMENTED)."""
    servers, servicers, channels = [], [], []
    for i in range(num_ps):
        servicer = PserverServicer(
            Parameters(), create_optimizer(opt_type, opt_args),
            ps_id=i, num_ps=num_ps, **kwargs,
        )
        server = grpc_utils.build_server(max_workers=8)
        if legacy_wire:
            handlers = {}
            for name, (req_cls, res_cls) in rpc.SERVICES[
                    "elasticdl_tpu.PServer"].items():
                if name.endswith("_frame"):
                    continue
                handlers[name] = grpc.unary_unary_rpc_method_handler(
                    getattr(servicer, name),
                    request_deserializer=req_cls.FromString,
                    response_serializer=res_cls.SerializeToString,
                )
            server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    "elasticdl_tpu.PServer", handlers),
            ))
        else:
            rpc.add_pserver_servicer(servicer, server)
        port = server.add_insecure_port("[::]:0")
        server.start()
        channel = grpc_utils.build_channel("localhost:%d" % port)
        grpc_utils.wait_for_channel_ready(channel)
        servers.append(server)
        servicers.append(servicer)
        channels.append(channel)
    return (PSClient(channels, frame_wire=frame_wire), servicers,
            servers)


def stop_all(servers):
    for s in servers:
        s.stop(grace=None)


def _dense(seed=0, n=3):
    rng = np.random.RandomState(seed)
    return {"layer%d/w" % i: rng.rand(4).astype(np.float32)
            for i in range(n)}


# -- negotiation ----------------------------------------------------------


def test_auto_upgrades_after_first_legacy_pull():
    client, _, servers = start_ps(num_ps=2, frame_wire="auto")
    try:
        assert client.frame_shards() == 0
        client.push_model(_dense())
        client.pull_dense_parameters(-1)  # legacy; reads frame_capable
        assert client.frame_shards() == 2
        # and the upgraded wire round-trips the same state
        _, _, pulled = client.pull_dense_parameters(-1)
        for k, v in _dense().items():
            np.testing.assert_array_equal(pulled[k], v)
        assert client.wire_stats["pull_dense_bytes_frame"] > 0
    finally:
        stop_all(servers)


def test_mode_off_never_uses_frames():
    client, _, servers = start_ps(num_ps=1, frame_wire="off")
    try:
        client.push_model(_dense())
        client.pull_dense_parameters(-1)
        client.pull_dense_parameters(-1)
        assert client.frame_shards() == 0
        assert client.wire_stats["pull_dense_bytes_frame"] == 0
        assert client.wire_stats["pull_dense_bytes_pb"] > 0
    finally:
        stop_all(servers)


def test_mode_on_forces_frames_from_first_rpc():
    client, _, servers = start_ps(num_ps=1, frame_wire="on")
    try:
        assert client.frame_shards() == 1
        client.push_model(_dense())
        _, _, pulled = client.pull_dense_parameters(-1)
        assert set(pulled) == set(_dense())
        assert client.wire_stats["pull_dense_bytes_pb"] == 0
    finally:
        stop_all(servers)


def test_rolling_downgrade_on_unimplemented():
    # The legacy server still ADVERTISES frame_capable (the field is in
    # its pull response), so an auto client upgrades, hits
    # UNIMPLEMENTED on the next framed RPC, and must fall back to the
    # pb wire without dropping the request.
    client, _, servers = start_ps(num_ps=1, frame_wire="auto",
                                  legacy_wire=True)
    try:
        client.push_model(_dense())
        client.pull_dense_parameters(-1)
        assert client.frame_shards() == 1  # trapped by the advert
        _, _, pulled = client.pull_dense_parameters(-1)  # downgrade
        assert client.frame_shards() == 0
        for k, v in _dense().items():
            np.testing.assert_array_equal(pulled[k], v)
        # pushes ride the pb wire after the downgrade, no re-probe
        accepted, _ = client.push_gradients(
            {k: np.ones(4, np.float32) for k in _dense()}, version=0)
        assert accepted
        assert client.wire_stats["push_gradient_bytes_frame"] == 0
    finally:
        stop_all(servers)


def test_mode_on_refuses_to_downgrade():
    client, _, servers = start_ps(num_ps=1, frame_wire="on",
                                  legacy_wire=True)
    try:
        with pytest.raises(grpc.RpcError) as err:
            client.pull_dense_parameters(-1)
        assert err.value.code() == grpc.StatusCode.UNIMPLEMENTED
    finally:
        stop_all(servers)


def test_push_downgrade_mid_flight_preserves_the_push():
    # Force the client to BELIEVE in frames against a legacy server:
    # the in-flight framed push must be re-sent on the pb wire and
    # actually apply.
    client, servicers, servers = start_ps(
        num_ps=1, frame_wire="auto", legacy_wire=True, use_async=True)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        client._frame_ok[0] = True  # the stale advert, distilled
        accepted, version = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=0)
        assert accepted and version == 1
        assert client.frame_shards() == 0
        _, _, pulled = client.pull_dense_parameters(-1)
        np.testing.assert_allclose(pulled["w"], 1 - 0.1 * 0.5)
    finally:
        stop_all(servers)


# -- apply identity -------------------------------------------------------


def test_frame_and_pb_apply_bit_identically():
    emb_ids = np.array([3, 7, 3, 11], np.int64)
    emb_vals = (np.arange(16, dtype=np.float32)
                .reshape(4, 4) / 7.0)

    def run(frame_wire):
        client, _, servers = start_ps(
            num_ps=2, frame_wire=frame_wire, use_async=True,
            opt_type="adam", opt_args="learning_rate=0.001")
        try:
            client.push_model(
                _dense(seed=5),
                embedding_infos=[{"name": "emb", "dim": 4,
                                  "initializer": "uniform"}])
            client.pull_embedding_vectors("emb", emb_ids, dim=4)
            for step in range(4):
                grads = {k: (v * (step + 1)).astype(np.float32)
                         for k, v in _dense(seed=5).items()}
                accepted, _ = client.push_gradients(
                    grads, {"emb": (emb_vals, emb_ids)}, version=step)
                assert accepted
            _, _, dense = client.pull_dense_parameters(-1)
            rows = client.pull_embedding_vectors("emb", emb_ids, dim=4)
            return dense, rows
        finally:
            stop_all(servers)

    dense_pb, rows_pb = run("off")
    dense_fr, rows_fr = run("on")
    assert set(dense_pb) == set(dense_fr)
    for k in dense_pb:
        np.testing.assert_array_equal(dense_pb[k], dense_fr[k])
    np.testing.assert_array_equal(rows_pb, rows_fr)


def test_bf16_wire_composes_with_frames():
    client, _, servers = start_ps(num_ps=1, frame_wire="on",
                                  use_async=True)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        accepted, _ = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=0)
        assert accepted
        _, _, pulled = client.pull_dense_parameters(-1)
        np.testing.assert_allclose(pulled["w"], 1 - 0.1 * 0.5)
    finally:
        stop_all(servers)
    # same apply, bf16-compressed frame push
    client, _, servers = start_ps(num_ps=1, frame_wire="on",
                                  use_async=True)
    try:
        client.wire_dtype = "bfloat16"
        client.push_model({"w": np.ones(4, np.float32)})
        accepted, _ = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=0)
        assert accepted
        _, _, pulled = client.pull_dense_parameters(-1)
        # 0.5 and 1.0 are exact in bf16, so even the compressed wire
        # applies exactly
        np.testing.assert_allclose(pulled["w"], 1 - 0.1 * 0.5)
        assert client.wire_stats["push_gradient_bytes_frame"] > 0
    finally:
        stop_all(servers)


# -- generation fencing reads the HEADER, not the payload -----------------


def _raw_stub(servers_addr_channel):
    return servers_addr_channel


def test_fence_rejects_before_decode():
    client, servicers, servers = start_ps(num_ps=1, frame_wire="on",
                                          use_async=True)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        stub = client._stubs[0]
        # A blob whose PAYLOAD is torn (ev/ table with no ei/ ids —
        # decode_grads_frame refuses it) but whose header meta is
        # clean.  Stamped by a dead generation, the fence must answer
        # accepted=False WITHOUT ever reaching the decode error.
        torn = tensor_codec.encode_frame(
            {"ev/emb": np.ones((2, 2), np.float32)},
            kind=tensor_codec.GRADS_FRAME_KIND,
            meta={"generation": servicers[0].generation + 1,
                  "learning_rate": 0.0})
        res = stub.push_gradients_frame(torn)
        assert not res.accepted
        assert servicers[0].counters["push_gen_rejected"] == 1
        # Same torn payload stamped with the LIVE generation now hits
        # the decoder and must be a loud INVALID_ARGUMENT.
        torn_live = tensor_codec.encode_frame(
            {"ev/emb": np.ones((2, 2), np.float32)},
            kind=tensor_codec.GRADS_FRAME_KIND,
            meta={"generation": servicers[0].generation,
                  "learning_rate": 0.0})
        with pytest.raises(grpc.RpcError) as err:
            stub.push_gradients_frame(torn_live)
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        stop_all(servers)


def test_client_learns_generation_from_frame_pulls():
    client, servicers, servers = start_ps(num_ps=1, frame_wire="on")
    try:
        client.push_model(_dense())
        assert client.known_generation(0) == 0
        client.pull_dense_parameters(-1)
        assert client.known_generation(0) == servicers[0].generation
    finally:
        stop_all(servers)


# -- hostile frames over the live wire ------------------------------------


HOSTILE_BLOBS = [
    ("truncated", lambda good: good[: len(good) - 7]),
    ("foreign_magic", lambda good: b"NOPE" + good[4:]),
    ("lying_length",
     lambda good: good[:4] + (2 ** 31).to_bytes(4, "little")
     + good[8:]),
    ("garbage", lambda good: b"\xff" * 64),
]


@pytest.mark.parametrize("name,mangle", HOSTILE_BLOBS)
def test_hostile_push_blobs_are_invalid_argument(name, mangle):
    client, _, servers = start_ps(num_ps=1, frame_wire="on",
                                  use_async=True)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        good = tensor_codec.encode_grads_frame(
            dense={"w": np.full(4, 0.5, np.float32)}, version=0)
        stub = client._stubs[0]
        with pytest.raises(grpc.RpcError) as err:
            stub.push_gradients_frame(mangle(good))
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT, \
            name
        # the servicer survived, on the SAME channel: a good framed
        # push still applies
        accepted, version = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=0)
        assert accepted and version == 1
    finally:
        stop_all(servers)


def test_hostile_dtype_and_meta_are_invalid_argument():
    client, _, servers = start_ps(num_ps=1, frame_wire="on",
                                  use_async=True)
    try:
        client.push_model({"w": np.ones(4, np.float32)})
        stub = client._stubs[0]
        # dtype smuggling: header says object — the codec must refuse
        # to materialize it
        good = tensor_codec.encode_grads_frame(
            dense={"w": np.full(4, 0.5, np.float32)}, version=0)
        evil = good.replace(b'"float32"', b'"object "', 1)
        with pytest.raises(grpc.RpcError) as err:
            stub.push_gradients_frame(evil)
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        # meta smuggling: generation that is not an int
        lying = tensor_codec.encode_frame(
            {"d/w": np.full(4, 0.5, np.float32)},
            kind=tensor_codec.GRADS_FRAME_KIND,
            meta={"generation": ["not", "an", "int"]})
        with pytest.raises(grpc.RpcError) as err:
            stub.push_gradients_frame(lying)
        assert err.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        accepted, _ = client.push_gradients(
            {"w": np.full(4, 0.5, np.float32)}, version=0)
        assert accepted
    finally:
        stop_all(servers)


# -- wire accounting ------------------------------------------------------


def test_wire_stats_attribute_bytes_per_encoding():
    client, servicers, servers = start_ps(num_ps=1, frame_wire="auto",
                                          use_async=True)
    try:
        client.push_model({"w": np.ones(8, np.float32)})
        client.pull_dense_parameters(-1)   # legacy leg
        assert client.wire_stats["pull_dense_bytes_pb"] > 0
        client.pull_dense_parameters(-1)   # upgraded leg
        assert client.wire_stats["pull_dense_bytes_frame"] > 0
        client.push_gradients({"w": np.ones(8, np.float32)}, version=0)
        assert client.wire_stats["push_gradient_bytes_frame"] > 0
        assert client.wire_stats["push_gradient_bytes_pb"] == 0
        # server-side mirror (surfaced on /statz + /metrics)
        wire = servicers[0].wire_counters
        assert wire["push_payload_frame"] > 0
        assert wire["pull_dense_payload_frame"] > 0
        assert wire["pull_dense_payload_pb"] > 0
        # frame decode-copy on the server is upcast-only: zero at f32
        assert wire["push_decode_copy_frame"] == 0
    finally:
        stop_all(servers)
