"""Observability plane (docs/observability.md): span API + flight
recorder (utils/tracing.py), gRPC trace propagation, the /tracez
endpoint, telemetry piggybacked on progress RPCs, Timing snapshot
race-safety, prom escaping, process log identity, and the EL009 lint
family."""

import json
import os
import threading
import time
import urllib.request

import pytest

from elasticdl_tpu.master.journal import JournalWriter, replay_journal
from elasticdl_tpu.master.servicer import (
    MasterServicer,
    create_master_service,
)
from elasticdl_tpu.master.status_server import StatusServer, collect_status
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import grpc_utils, tracing
from elasticdl_tpu.utils.prom import prometheus_line, to_prometheus
from elasticdl_tpu.utils.retry import RetryPolicy
from elasticdl_tpu.utils.timing import Timing
from elasticdl_tpu.worker.master_client import MasterClient
from tests.test_utils import create_master, create_master_client


@pytest.fixture
def clean_tracer():
    """The process-global tracer, ring cleared, attrs restored after —
    in-process tests share it across the 'roles' they simulate."""
    tracer = tracing.default_tracer()
    saved_attrs = tracer.process_attrs
    saved_enabled = tracer.enabled
    tracer.enabled = True
    tracer.recorder.clear()
    yield tracer
    tracer._attrs = saved_attrs
    tracer.enabled = saved_enabled
    tracer.recorder.clear()


def _get(port, path):
    with urllib.request.urlopen(
        "http://127.0.0.1:%d%s" % (port, path), timeout=10
    ) as resp:
        return resp.status, resp.read().decode()


# -- span API / flight recorder ----------------------------------------------

def test_span_nesting_and_context(clean_tracer):
    with tracing.span("outer", kind="test") as outer:
        outer_ctx = tracing.current()
        with tracing.span("inner") as inner:
            assert inner.trace == outer.trace  # one trace
            assert inner.parent == outer.span_id
            tracing.event("marker", x=1)
        assert tracing.current() == outer_ctx
    assert tracing.current() == (None, None)
    events = clean_tracer.recorder.snapshot()
    names = [(e["ph"], e["name"]) for e in events]
    assert names == [("B", "outer"), ("B", "inner"), ("i", "marker"),
                     ("E", "inner"), ("E", "outer")]
    marker = events[2]
    assert marker["trace"] == outer.trace
    assert marker["span"] == inner.span_id


def test_span_error_recorded_and_stack_unwound(clean_tracer):
    with pytest.raises(ValueError):
        with tracing.span("failing"):
            raise ValueError("boom")
    assert tracing.current() == (None, None)
    end = clean_tracer.recorder.snapshot()[-1]
    assert end["ph"] == "E" and "boom" in end["error"]


def test_ring_wraparound_keeps_newest():
    rec = tracing.FlightRecorder(capacity=8)
    for i in range(20):
        rec.record({"n": i})
    events = rec.snapshot()
    assert len(events) == 8
    assert [e["n"] for e in events] == list(range(12, 20))
    assert rec.dropped == 12


def test_disabled_tracer_records_nothing():
    tracer = tracing.Tracer(recorder=tracing.FlightRecorder(16),
                            enabled=False)
    with tracer.span("x") as sp:
        assert sp is None
        tracer.event("y")
    assert len(tracer.recorder) == 0


def test_chrome_export_shapes(clean_tracer):
    with tracing.span("work", step=3):
        tracing.event("tick")
    open_span = clean_tracer.start_span("leaked")
    chrome = tracing.to_chrome(clean_tracer.recorder.snapshot())
    clean_tracer.end_span(open_span)
    rows = {row["name"]: row for row in chrome["traceEvents"]}
    assert rows["work"]["ph"] == "X" and rows["work"]["dur"] >= 0
    assert rows["work"]["args"]["step"] == 3
    assert rows["tick"]["ph"] == "i"
    # unclosed span renders visibly instead of vanishing
    assert rows["leaked"]["ph"] == "i"
    assert rows["leaked"]["args"]["unclosed"] is True


def test_dump_load_roundtrip(tmp_path, clean_tracer):
    clean_tracer.configure(role="testproc")
    with tracing.span("alpha"):
        pass
    path = clean_tracer.dump(str(tmp_path))
    assert path.endswith(".trace.json")
    events = tracing.load_dumps(str(tmp_path))
    assert any(e["name"] == "alpha" for e in events)
    assert all(e["role"] == "testproc" for e in events)


def test_arm_crash_dump_sigterm_still_terminates(tmp_path):
    """A process with the DEFAULT SIGTERM disposition (master, router)
    must still die on SIGTERM after arming the crash dump — the
    handler dumps the ring, restores SIG_DFL, and re-delivers; and the
    dump must actually land."""
    import signal
    import subprocess
    import sys

    code = (
        "import os, signal, time\n"
        "from elasticdl_tpu.utils import tracing\n"
        "tracing.configure(role='master')\n"
        "tracing.arm_crash_dump()\n"
        "tracing.event('alive')\n"
        "os.kill(os.getpid(), signal.SIGTERM)\n"
        "time.sleep(30)\n"           # must never be reached
        "print('SURVIVED')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        env=dict(os.environ, ELASTICDL_TRACE_DIR=str(tmp_path),
                 JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == -signal.SIGTERM
    assert "SURVIVED" not in proc.stdout
    events = tracing.load_dumps(str(tmp_path))
    assert any(e["name"] == "sigterm" for e in events)


def test_trace_components_link_trace_merges():
    events = [
        {"trace": "a", "name": "x"},
        {"trace": "b", "name": "y", "link_trace": "a"},
        {"trace": "c", "name": "z"},
    ]
    comps = tracing.trace_components(events)
    assert sorted(len(c) for c in comps) == [1, 2]
    linked = next(c for c in comps if len(c) == 2)
    assert {e["name"] for e in linked} == {"x", "y"}


# -- gRPC propagation through a real channel ---------------------------------

def test_span_propagates_through_real_grpc_channel(clean_tracer):
    master = create_master(training_shards=[("f", 0, 64)],
                           records_per_task=32)
    mc = create_master_client(master)
    try:
        with tracing.span("worker.task", task=0) as task_span:
            task = mc.get_task()
            assert task.id >= 0
            mc.report_task_result(task.id)
        events = clean_tracer.recorder.snapshot()
        # client span, server span, and the master's task.completed
        # breadcrumb all share the task span's trace
        client = [e for e in events if e["ph"] == "B"
                  and e["name"] == "rpc.client/report_task_result"]
        server = [e for e in events if e["ph"] == "B" and e["name"]
                  .startswith("rpc.server/")
                  and e["name"].endswith("report_task_result")]
        done = [e for e in events if e["name"] == "task.completed"]
        assert client and server and done
        assert client[0]["trace"] == task_span.trace
        assert server[0]["trace"] == task_span.trace
        assert server[0]["parent"] == client[0]["span"]
        assert done[0]["trace"] == task_span.trace
    finally:
        master.stop()


def test_inject_extract_roundtrip(clean_tracer):
    assert tracing.inject(None) is None  # no open span: no metadata
    with tracing.span("ctx") as sp:
        md = tracing.inject([("other", "kept")])
        trace, parent = tracing.Tracer.extract(md)
        assert trace == sp.trace and parent == sp.span_id
        assert ("other", "kept") in md


# -- the connected-trace recovery assertion (in-process master kill) ---------

def test_master_restart_yields_one_connected_trace(tmp_path,
                                                   clean_tracer):
    """The cpu_master_kill drill's trace gate, in miniature and
    in-process: worker trace (kill-window retries + post-recovery task
    completion) and the restarted master's journal-replay trace form
    ONE component via the link_trace stamp."""
    jdir = str(tmp_path)
    port = grpc_utils.find_free_port()

    tm1 = TaskManager(training_shards=[("f", 0, 96)],
                      records_per_task=32, num_epochs=1)
    tm1.attach_journal(JournalWriter(jdir), bootstrap=True)
    servicer1 = MasterServicer(tm1)
    server1, _ = create_master_service(servicer1, port=port)

    channel = grpc_utils.build_channel("localhost:%d" % port)
    grpc_utils.wait_for_channel_ready(channel)
    mc = MasterClient(
        channel, worker_id=0, addr="localhost:%d" % port,
        retry=RetryPolicy(name="test_mc", deadline_secs=30.0,
                          base_delay_secs=0.05, max_delay_secs=0.2),
    )

    with tracing.span("worker.run", worker=0):
        with tracing.span("worker.task"):
            task = mc.get_task()
            mc.report_task_result(task.id)

        # "kill" the master; the journal survives.  Wait for the stop
        # to complete — the listener must actually release the port
        # before the in-process restart can rebind it.
        server1.stop(grace=0).wait(timeout=10)

        # restart flow as master/main.py runs it: replay under a span,
        # then stamp every later master event with the link back
        with tracing.span("master.journal_replay") as replay_span:
            state = replay_journal(jdir)
            tracing.event("journal.replayed", restarts=state.restarts)
        clean_tracer.configure(restart=state.restarts + 1,
                               link_trace=replay_span.trace)

        restart_done = threading.Event()
        restart_errors = []
        # keep the restarted server referenced past the thread's exit
        # (a dropped grpc.Server is GC'd and its listener closes)
        restarted = {}

        def restart_master():
            try:
                # small outage window so the worker's retry fires
                time.sleep(0.4)
                tm2 = TaskManager(training_shards=[("f", 0, 96)],
                                  records_per_task=32, num_epochs=1)
                tm2.restore_from_journal(state)
                writer = JournalWriter(jdir)
                writer.append({"ev": "restart"})
                tm2.attach_journal(writer, bootstrap=False)
                servicer2 = MasterServicer(tm2)
                servicer2.restore_from_journal(state)
                # same-port rebind can race the old listener's
                # teardown in-process: add_insecure_port returns 0 on
                # failure, so retry until the port is really ours
                bound = 0
                for _ in range(100):
                    server2, bound = create_master_service(
                        servicer2, port=port)
                    if bound == port:
                        restarted["server"] = server2
                        break
                    server2.stop(grace=0)
                    time.sleep(0.1)
                assert bound == port, "could not rebind port"
                restart_done.set()
            except Exception as e:  # noqa: BLE001 — surfaced below
                import traceback
                restart_errors.append(
                    "%s\n%s" % (e, traceback.format_exc()))

        t = threading.Thread(target=restart_master, daemon=True)
        t.start()
        # outage-riding: this fetch retries through the dead window
        # and lands on master #2 (post-recovery task completion)
        with tracing.span("worker.task"):
            task = mc.get_task()
            assert task.id >= 0
            mc.report_task_result(task.id)
        t.join(timeout=30)
        assert not restart_errors, restart_errors
        assert restart_done.is_set()
    restarted["server"].stop(grace=0)

    events = clean_tracer.recorder.snapshot()
    comps = tracing.trace_components(events)
    incident = comps[0]  # largest component
    names = {e["name"] for e in incident}
    # kill evidence, recovery evidence, and the first post-recovery
    # completion — all in ONE connected component
    assert "rpc_retry" in names
    assert "journal.replayed" in names
    assert "task.completed" in names
    # and the completion happened on the RESTARTED incarnation
    completions = [e for e in incident if e["name"] == "task.completed"]
    assert any(e.get("restart") == 1 for e in completions)


# -- /tracez + concurrent-mutation hammers -----------------------------------

def test_status_endpoints_under_concurrent_mutation(clean_tracer):
    master = create_master(training_shards=[("f", 0, 4096)],
                           records_per_task=16, rendezvous=True)
    server = StatusServer(
        master.task_manager,
        rendezvous_server=master.rendezvous_server,
        servicer=master.servicer,
        host="127.0.0.1",
    )
    server.start()
    stop = threading.Event()
    errors = []

    def mutate():
        mc = create_master_client(master)
        i = 0
        try:
            while not stop.is_set():
                i += 1
                req = pb.ReportBatchDoneRequest(
                    worker_id=i % 4, record_count=16,
                    steps_per_sec=float(i), sync_fraction=0.5,
                    steps_done=i,
                )
                master.servicer.report_batch_done(req)
                with tracing.span("hammer", i=i):
                    tracing.event("tick-%d" % (i % 7))
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=mutate, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 2.0
        while time.time() < deadline:
            for path in ("/status", "/metrics", "/tracez",
                         "/tracez?fmt=chrome"):
                code, body = _get(server.port, path)
                assert code == 200
                if path == "/status":
                    status = json.loads(body)
                    if "telemetry" in status:
                        assert status["telemetry"]["job"][
                            "workers_reporting"] >= 1
                elif path.startswith("/tracez"):
                    json.loads(body)  # parseable mid-hammer
                else:
                    assert "elasticdl_tasks_todo" in body
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        server.stop()
        master.stop()
    assert not errors


def test_serving_statz_metrics_tracez_under_load(tmp_path,
                                                 clean_tracer):
    """The serving replica's observability surface under concurrent
    predict traffic: /statz, /metrics, and /tracez all answer
    parseable 200s while request threads mutate the Timing stats and
    the flight recorder."""
    import http.client

    from elasticdl_tpu.serving.batcher import BatchConfig
    from elasticdl_tpu.serving.server import (
        ModelEndpoint,
        build_server as build_serving_server,
    )
    from tests.test_serving_batcher import _linear_export

    _linear_export(tmp_path / "e")
    endpoint = ModelEndpoint(
        str(tmp_path / "e"),
        batching=BatchConfig(max_batch_size=4, batch_timeout_ms=2.0,
                             warm=True))
    server = build_serving_server(endpoint, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    stop = threading.Event()
    errors = []

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=30)
        try:
            k = 0
            while not stop.is_set():
                k += 1
                with tracing.span("client.predict", k=k):
                    conn.request(
                        "POST", "/v1/models/lin:predict",
                        body=json.dumps({"instances": [[k, 0, 0, 0]]}))
                    assert conn.getresponse().read()
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)
        finally:
            conn.close()

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        deadline = time.time() + 2.0
        while time.time() < deadline:
            for path in ("/statz", "/metrics", "/tracez",
                         "/tracez?fmt=chrome"):
                code, body = _get(port, path)
                assert code == 200
                if path == "/statz":
                    json.loads(body)
                elif path.startswith("/tracez"):
                    json.loads(body)
                else:
                    assert "elasticdl_serving_requests" in body
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        server.shutdown()
        server.server_close()
        endpoint.close()
    assert not errors


def test_tracez_endpoint_payload(clean_tracer):
    clean_tracer.configure(role="master")
    with tracing.span("visible"):
        pass
    master = create_master(training_shards=[("f", 0, 32)],
                           records_per_task=32)
    server = StatusServer(master.task_manager, host="127.0.0.1")
    server.start()
    try:
        code, body = _get(server.port, "/tracez")
        payload = json.loads(body)
        assert code == 200
        assert payload["process"]["role"] == "master"
        assert any(e["name"] == "visible"
                   for e in payload["events"])
        code, body = _get(server.port, "/tracez?fmt=chrome")
        chrome = json.loads(body)
        assert any(row["name"] == "visible"
                   for row in chrome["traceEvents"])
    finally:
        server.stop()
        master.stop()


# -- telemetry piggyback ------------------------------------------------------

def test_telemetry_rides_progress_rpc_end_to_end(clean_tracer):
    master = create_master(training_shards=[("f", 0, 64)],
                           records_per_task=32)
    mc = create_master_client(master, worker_id=3)
    try:
        mc.report_batch_done(32, telemetry={
            "steps_per_sec": 12.5, "sync_fraction": 0.125,
            "push_staleness": 2.0, "window_size": 4.0,
            "steps_done": 40,
        })
        telemetry = master.servicer.telemetry()
        worker = telemetry["workers"][3]
        assert worker["steps_per_sec"] == 12.5
        assert worker["sync_fraction"] == 0.125
        assert worker["push_staleness"] == 2.0
        assert worker["window_size"] == 4.0
        assert worker["steps_done"] == 40
        assert worker["age_secs"] < 10
        assert telemetry["job"]["steps_per_sec"] == 12.5
        assert telemetry["job"]["workers_reporting"] == 1

        # a second worker sums into the job aggregate
        mc2 = create_master_client(master, worker_id=4)
        mc2.report_batch_done(32, telemetry={
            "steps_per_sec": 7.5, "steps_done": 8})
        assert master.servicer.telemetry()["job"][
            "steps_per_sec"] == 20.0

        status = collect_status(master.task_manager,
                                servicer=master.servicer)
        text = to_prometheus(status)
        assert "elasticdl_job_steps_per_sec" in text
        assert 'elasticdl_worker_steps_per_sec{worker="3"} 12.5' in text

        # stale workers (> 60 s) fall out of the aggregate AND out of
        # /metrics (a scraper must not sum a dead worker's last rate)
        # but stay visible in /status JSON with their age
        stale = master.servicer.telemetry(now=time.time() + 300)
        assert stale["job"]["workers_reporting"] == 0
        assert stale["job"]["steps_per_sec"] == 0.0
        assert 3 in stale["workers"]
        assert stale["workers"][3]["fresh"] is False
        stale_text = to_prometheus(
            {"tasks": status["tasks"], "finished": status["finished"],
             "telemetry": stale})
        assert "elasticdl_worker_steps_per_sec" not in stale_text
        assert "elasticdl_telemetry_workers_reporting 0" in stale_text

        # long-dead workers (> 15 min) are EVICTED outright: the dict
        # and the /status payload stay bounded over elastic churn
        evicted = master.servicer.telemetry(now=time.time() + 3600)
        assert evicted["workers"] == {}
        assert master.servicer.telemetry()["workers"] == {}
    finally:
        master.stop()


def test_telemetry_absent_without_steps(clean_tracer):
    master = create_master(training_shards=[("f", 0, 64)],
                           records_per_task=32)
    mc = create_master_client(master, worker_id=0)
    try:
        mc.report_batch_done(32)  # legacy form: no telemetry fields
        assert master.servicer.telemetry()["workers"] == {}
        status = collect_status(master.task_manager,
                                servicer=master.servicer)
        assert "telemetry" not in status
    finally:
        master.stop()


def test_shard_service_telemetry_fn_feeds_reports(clean_tracer):
    from elasticdl_tpu.worker.data_shard_service import DataShardService

    master = create_master(training_shards=[("f", 0, 32)],
                           records_per_task=32)
    mc = create_master_client(master, worker_id=7)
    try:
        calls = []

        def telemetry_fn():
            calls.append(1)
            return {"steps_per_sec": 3.0, "steps_done": len(calls)}

        ds = DataShardService(mc, batch_size=32,
                              telemetry_fn=telemetry_fn)
        task = ds.fetch_task()
        ds.report_batch_done()  # drains the shard -> flush + done
        assert task is not None and calls
        assert master.servicer.worker_telemetry[7][
            "steps_per_sec"] == 3.0
    finally:
        master.stop()


def test_worker_telemetry_snapshot_shapes():
    """Worker._telemetry_snapshot: steps/s over the mark interval,
    sync fraction from Timing, staleness from the trainer hook."""
    from elasticdl_tpu.worker.worker import Worker

    class _Trainer:
        def push_staleness(self):
            return 2.0

    worker = Worker.__new__(Worker)
    worker._trainer = _Trainer()
    worker.timing = Timing()
    worker._steps = 0
    worker._tele_mark = (None, 0)
    first = worker._telemetry_snapshot()
    assert first["steps_done"] == 0
    assert "steps_per_sec" not in first  # no interval yet
    worker._steps = 50
    worker._tele_mark = (time.monotonic() - 2.0, 0)
    worker.timing.observe("window_dispatch", 3.0)
    worker.timing.observe("loss_sync", 1.0)
    worker.timing.bump("fused_windows", 10)
    worker.timing.bump("fused_steps_run", 40)
    snap = worker._telemetry_snapshot()
    assert 20.0 <= snap["steps_per_sec"] <= 30.0
    assert snap["sync_fraction"] == 0.25
    assert snap["push_staleness"] == 2.0
    assert snap["window_size"] == 4.0
    assert snap["steps_done"] == 50


# -- Timing snapshot race-safety ---------------------------------------------

class _ListLogger:
    def __init__(self):
        self.lines = []

    def info(self, fmt, *args):
        self.lines.append(fmt % args if args else fmt)


def test_timing_snapshot_hammer():
    """Writers minting NEW phase/counter names nonstop while every
    snapshot path runs concurrently: no 'dict changed size' blowups,
    and the final counts are exact."""
    timing = Timing(logger=_ListLogger())
    stop = threading.Event()
    errors = []
    WRITERS, PER_WRITER = 4, 400

    def write(seed):
        try:
            for i in range(PER_WRITER):
                timing.bump("shared")
                timing.bump("w%d-ev%d" % (seed, i))
                timing.observe("w%d-phase%d" % (seed, i), 0.001)
                with timing.timeit("w%d-timed%d" % (seed, i % 17)):
                    pass
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    def read():
        try:
            while not stop.is_set():
                timing.summary()
                timing.counters()
                timing.report()
                timing.sync_fraction("a", "b")
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    readers = [threading.Thread(target=read, daemon=True)
               for _ in range(2)]
    writers = [threading.Thread(target=write, args=(s,), daemon=True)
               for s in range(WRITERS)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join(timeout=60)
    stop.set()
    for t in readers:
        t.join(timeout=10)
    assert not errors
    assert timing.counters()["shared"] == WRITERS * PER_WRITER
    summary = timing.summary()
    assert summary["w0-phase0"]["count"] == 1


# -- prom escaping ------------------------------------------------------------

def test_prometheus_label_escaping():
    line = prometheus_line("m", 1, path='C:\\dir "x"\nnext')
    assert line == 'm{path="C:\\\\dir \\"x\\"\\nnext"} 1'
    assert prometheus_line("m", 2) == "m 2"
    # sorted label order, multiple labels
    line = prometheus_line("m", 3, b="2", a="1")
    assert line == 'm{a="1",b="2"} 3'


def test_status_server_reexports_renderers():
    # historical import path keeps working after the utils/prom move
    from elasticdl_tpu.master import status_server
    from elasticdl_tpu.utils import prom

    assert status_server.to_prometheus is prom.to_prometheus
    assert status_server.prometheus_line is prom.prometheus_line
    assert status_server.serving_to_prometheus is (
        prom.serving_to_prometheus)
    assert status_server.fleet_to_prometheus is prom.fleet_to_prometheus


# -- process log identity -----------------------------------------------------

def test_log_identity_prefix():
    import logging as _logging

    from elasticdl_tpu.utils.logging import (
        _IdentityFormatter,
        get_process_identity,
        set_process_identity,
    )

    saved = get_process_identity()
    try:
        set_process_identity("ps", rank=1, generation=2)
        fmt = _IdentityFormatter("%(identity)s%(message)s")
        record = _logging.LogRecord("n", _logging.INFO, "p", 1,
                                    "hello", (), None)
        assert fmt.format(record) == "[ps-1@g2] hello"
        set_process_identity("worker", rank=0)
        assert fmt.format(record) == "[worker-0] hello"
    finally:
        # restore whatever identity the test process had
        from elasticdl_tpu.utils.logging import _identity
        _identity["label"] = saved


# -- EL009 lint family --------------------------------------------------------

def test_el009_flags_unclosed_start_span():
    from tools.elastic_lint import check_source

    bad = (
        "def f(tracer):\n"
        "    sp = tracer.start_span('x')\n"
        "    do_work()\n"
        "    tracer.end_span(sp)\n"  # not in a finally: leaks on raise
    )
    findings = [f for f in check_source(bad, "fixture.py")
                if f.rule == "EL009"]
    assert len(findings) == 1
    assert "start_span" in findings[0].symbol


def test_el009_accepts_with_form_and_finally_form():
    from tools.elastic_lint import check_source

    good = (
        "def f(tracer):\n"
        "    with tracer.span('x'):\n"
        "        do_work()\n"
        "    with tracer.start_span_ctx() as sp:\n"
        "        pass\n"
        "\n"
        "def g(tracer):\n"
        "    sp = tracer.start_span('x')\n"
        "    try:\n"
        "        do_work()\n"
        "    finally:\n"
        "        tracer.end_span(sp)\n"
        "\n"
        "def h(tracer):\n"
        "    with tracer.start_span('managed'):\n"
        "        pass\n"
    )
    findings = [f for f in check_source(good, "fixture.py")
                if f.rule == "EL009"]
    assert findings == []


def test_el006_blocks_recorder_dump_under_lock_not_record():
    from tools.elastic_lint import check_source

    bad = (
        "import threading\n"
        "from elasticdl_tpu.utils.tracing import FlightRecorder\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._recorder = FlightRecorder()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            self._recorder.dump('/tmp/x')\n"
    )
    findings = [f for f in check_source(bad, "fixture.py")
                if f.rule == "EL006"]
    assert len(findings) == 1
    assert "flight-recorder" in findings[0].message

    good = bad.replace(".dump('/tmp/x')", ".record({'a': 1})")
    findings = [f for f in check_source(good, "fixture.py")
                if f.rule == "EL006"]
    assert findings == []


# -- retry events -------------------------------------------------------------

def test_retry_policy_records_trace_events(clean_tracer):
    calls = {"n": 0}

    class _Transient(Exception):
        pass

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise _Transient("nope")
        return "ok"

    policy = RetryPolicy(
        name="test", max_attempts=5, deadline_secs=None,
        base_delay_secs=0.0, jitter=0.0,
        retryable=lambda e: isinstance(e, _Transient),
        sleep=lambda _s: None,
    )
    with tracing.span("owner") as sp:
        assert policy.call(flaky, description="flaky") == "ok"
    retries = [e for e in clean_tracer.recorder.snapshot()
               if e["name"] == "rpc_retry"]
    assert len(retries) == 2
    # inherited the caller's context: the outage evidence lands in the
    # owning span's trace
    assert all(e["trace"] == sp.trace for e in retries)
