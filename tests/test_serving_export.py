"""Servable export: StableHLO + npz that serve WITHOUT the framework
(VERDICT r2 #6 — the reference's SavedModel role, callbacks.py:23-66).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from elasticdl_tpu.models import mnist
from elasticdl_tpu.models.callbacks import ModelExporter
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer


def _trained_export(tmp_path):
    spec = mnist.model_spec()
    trainer = CollectiveTrainer(spec, batch_size=8)
    xs, ys = mnist.synthetic_data(n=8)
    trainer.train_minibatch(xs, ys)
    export_dir = str(tmp_path / "export")
    ModelExporter(export_dir, model_name="mnist").on_train_end(trainer)
    return trainer, export_dir, xs


def test_servable_layout_and_manifest(tmp_path):
    _, export_dir, _ = _trained_export(tmp_path)
    for fname in ("model.npz", "model.stablehlo", "manifest.json"):
        assert os.path.exists(os.path.join(export_dir, fname)), fname
    with open(os.path.join(export_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["format"] == "elasticdl_tpu_servable_v2"
    assert manifest["model_name"] == "mnist"
    assert "tpu" in manifest["platforms"]
    sig = manifest["input_signature"]
    assert sig["shape"][1:] == [28, 28]


def test_servable_matches_trainer_predictions(tmp_path):
    trainer, export_dir, xs = _trained_export(tmp_path)
    from elasticdl_tpu.serving.loader import load_servable

    model = load_servable(export_dir)
    got = np.asarray(model.predict(np.asarray(xs)))
    want = trainer.predict_minibatch(xs)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                               atol=1e-5)


_STANDALONE = r"""
import json
import sys

import numpy as np

sys.path.insert(0, %(repo)r)
import jax

jax.config.update("jax_platforms", "cpu")

from elasticdl_tpu.serving.loader import load_servable

model = load_servable(%(export_dir)r)
shape = [
    8 if d is None else d  # polymorphic batch: caller picks the batch
    for d in model.manifest["input_signature"]["shape"]
]
x = np.zeros(shape, np.float32)
out = np.asarray(model.predict(x))
banned = [
    m for m in sys.modules
    if m.startswith(("elasticdl_tpu.master", "elasticdl_tpu.worker",
                     "elasticdl_tpu.ps", "elasticdl_tpu.models"))
]
print(json.dumps({"shape": list(out.shape), "banned": banned}))
"""


def test_servable_loads_without_framework(tmp_path):
    """The VERDICT 'done' bar: a fresh process loads the export and runs
    inference importing NOTHING from master/worker/ps (nor the model
    zoo)."""
    _, export_dir, _ = _trained_export(tmp_path)
    code = _STANDALONE % {
        "repo": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        "export_dir": export_dir,
    }
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ELASTICDL_TPU_PLATFORM="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["banned"] == []
    assert result["shape"] == [8, 10]


def test_dense_overrides_take_precedence(tmp_path):
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    params = {"w": np.ones((4, 2), np.float32)}
    newer = {"w": np.full((4, 2), 3.0, np.float32)}
    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x @ p["w"],
        params,
        np.zeros((1, 4), np.float32),
        dense_overrides=newer,
        platforms=("cpu",),
    )
    model = load_servable(str(tmp_path / "e"))
    np.testing.assert_array_equal(model.params["w"], newer["w"])
    out = np.asarray(model.predict(np.ones((1, 4), np.float32)))
    np.testing.assert_allclose(out, np.full((1, 2), 12.0))


def test_embedding_lookup(tmp_path):
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x * p["s"],
        {"s": np.float32(2.0)},
        np.zeros((2, 3), np.float32),
        embeddings={"users": (np.array([5, 9]),
                              np.arange(8, dtype=np.float32)
                              .reshape(2, 4))},
        platforms=("cpu",),
    )
    model = load_servable(str(tmp_path / "e"))
    assert model.manifest["embedding_tables"] == ["users"]
    rows = model.lookup_embedding("users", [9, 7, 5])
    np.testing.assert_array_equal(rows[0], [4, 5, 6, 7])
    np.testing.assert_array_equal(rows[1], [0, 0, 0, 0])  # unknown id
    np.testing.assert_array_equal(rows[2], [0, 1, 2, 3])


def test_polymorphic_batch_export(tmp_path):
    """The servable accepts ANY batch size (symbolic leading dim), and
    a scalar aux input does not force the export monomorphic."""
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    manifest = export_servable(
        str(tmp_path / "e"),
        lambda p, x: x["v"] @ p["w"] * x["temp"],
        {"w": np.arange(8, dtype=np.float32).reshape(4, 2)},
        {"v": np.zeros((1, 4), np.float32),
         "temp": np.float32(1.0)},  # rank-0 leaf stays concrete
        platforms=("cpu",),
    )
    assert manifest["polymorphic_batch"] is True
    # metadata tells the truth: the batch dim is free, not the
    # example's 1 (rank-0 leaves keep their empty shape)
    assert manifest["input_signature"]["v"]["shape"] == [None, 4]
    assert manifest["input_signature"]["temp"]["shape"] == []
    model = load_servable(str(tmp_path / "e"))
    for batch in (1, 3, 7):  # != the example's batch of 1
        out = np.asarray(model.predict(
            {"v": np.ones((batch, 4), np.float32),
             "temp": np.float32(2.0)}
        ))
        assert out.shape == (batch, 2)
        np.testing.assert_allclose(out[0], [24.0, 32.0])

    # Inputs that DISAGREE on their leading dim must not get a shared
    # batch symbol (the export would succeed but reject its own example
    # shapes at serving time): fixed-shape export instead.
    manifest2 = export_servable(
        str(tmp_path / "e2"),
        lambda p, x: x["a"].sum() + x["b"].sum() + p["w"],
        {"w": np.float32(0.0)},
        {"a": np.zeros((2, 3), np.float32),
         "b": np.zeros((5,), np.float32)},
        platforms=("cpu",),
    )
    assert manifest2["polymorphic_batch"] is False
    model2 = load_servable(str(tmp_path / "e2"))
    out2 = model2.predict({"a": np.ones((2, 3), np.float32),
                           "b": np.ones((5,), np.float32)})
    np.testing.assert_allclose(np.asarray(out2), 11.0)


def test_model_server_rest_surface(tmp_path):
    """The TF-Serving-role HTTP server over a servable export:
    metadata, :predict (instances), :lookup, and error paths — the
    REST shape clients of the reference's TF Serving deployment keep
    (model_handler.py:242-269)."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.server import ModelEndpoint, build_server

    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x @ p["w"],
        {"w": np.arange(8, dtype=np.float32).reshape(4, 2)},
        np.zeros((1, 4), np.float32),
        model_name="lin",
        embeddings={"users": (np.array([5, 9]),
                              np.arange(8, dtype=np.float32)
                              .reshape(2, 4))},
        platforms=("cpu",),
    )
    server = build_server(ModelEndpoint(str(tmp_path / "e")), port=0)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    base = "http://127.0.0.1:%d/v1/models/lin" % port

    def call(path, payload=None):
        req = urllib.request.Request(
            base + path,
            data=None if payload is None
            else _json.dumps(payload).encode(),
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return _json.loads(resp.read())

    try:
        meta = call("")
        assert meta["model_version_status"][0]["state"] == "AVAILABLE"
        assert meta["metadata"]["model_name"] == "lin"

        out = call(":predict", {"instances": [[1, 1, 1, 1],
                                              [0, 1, 0, 0]]})
        np.testing.assert_allclose(out["predictions"],
                                   [[12.0, 16.0], [2.0, 3.0]])

        vecs = call(":lookup", {"table": "users", "ids": [9, 7]})
        np.testing.assert_allclose(vecs["vectors"],
                                   [[4, 5, 6, 7], [0, 0, 0, 0]])

        with pytest.raises(urllib.error.HTTPError) as err:
            call(":predict", {"wrong_key": []})
        assert err.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as err:
            call(":nope", {})
        assert err.value.code == 404
    finally:
        server.shutdown()
        server.server_close()


def test_model_server_concurrent_predicts(tmp_path):
    """N threads hammer :predict concurrently; the endpoint lock keeps
    results correct and every request gets a response."""
    import json as _json
    import threading
    import urllib.request

    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.server import ModelEndpoint, build_server

    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x * p["s"],
        {"s": np.float32(3.0)},
        np.zeros((1, 2), np.float32),
        model_name="c",
        platforms=("cpu",),
    )
    server = build_server(ModelEndpoint(str(tmp_path / "e")), port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:%d/v1/models/c:predict" % port
    results = {}

    def hit(k):
        req = urllib.request.Request(
            url, data=_json.dumps(
                {"instances": [[k, k + 1]]}).encode())
        with urllib.request.urlopen(req, timeout=60) as resp:
            results[k] = _json.loads(resp.read())["predictions"]

    try:
        threads = [threading.Thread(target=hit, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert len(results) == 8
        for k, out in results.items():
            np.testing.assert_allclose(out, [[3.0 * k, 3.0 * (k + 1)]])
    finally:
        server.shutdown()
        server.server_close()


def test_embedding_lookup_duplicate_ids_keep_last(tmp_path):
    """A merged table carrying a duplicated id must serve the LAST
    stored row for it (the semantics of the dict-rebuild path the
    sorted index replaced — advisor r4)."""
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    ids = np.array([5, 9, 5])  # id 5 appears twice; last row wins
    values = np.arange(12, dtype=np.float32).reshape(3, 4)
    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x * p["s"],
        {"s": np.float32(1.0)},
        np.zeros((2, 3), np.float32),
        embeddings={"users": (ids, values)},
        platforms=("cpu",),
    )
    model = load_servable(str(tmp_path / "e"))
    rows = model.lookup_embedding("users", [5, 9])
    np.testing.assert_array_equal(rows[0], [8, 9, 10, 11])
    np.testing.assert_array_equal(rows[1], [4, 5, 6, 7])


def test_versioned_serving_hot_reload(tmp_path):
    """TF-Serving layout <base>/<N>/: the server serves the latest
    complete version and flips to v2 exported MID-SERVE without a
    restart (VERDICT r4 #6); an incomplete version dir (no manifest
    yet) is ignored."""
    import json as _json
    import threading
    import time as _time
    import urllib.request

    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.server import ModelEndpoint, build_server

    base = str(tmp_path / "models")

    def put(version, scale):
        export_servable(
            os.path.join(base, str(version)),
            lambda p, x: x * p["s"],
            {"s": np.float32(scale)},
            np.zeros((1, 2), np.float32),
            model_name="vm", version=version,
            platforms=("cpu",),
        )

    put(1, 2.0)
    # An in-flight export (files but no manifest yet) must never be
    # picked up.
    os.makedirs(os.path.join(base, "7"))
    with open(os.path.join(base, "7", "model.npz"), "wb") as f:
        f.write(b"partial")

    endpoint = ModelEndpoint(base, poll_interval=0.05)
    server = build_server(endpoint, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    root = "http://127.0.0.1:%d/v1/models/vm" % port

    def call(path, payload=None):
        req = urllib.request.Request(
            root + path,
            data=None if payload is None
            else _json.dumps(payload).encode(),
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return _json.loads(resp.read())

    try:
        meta = call("/metadata")  # the TF-Serving metadata alias
        assert meta["model_version_status"][0]["version"] == "1"
        out = call(":predict", {"instances": [[1, 10]]})
        np.testing.assert_allclose(out["predictions"], [[2.0, 20.0]])

        put(2, 5.0)
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            meta = call("")
            if meta["model_version_status"][0]["version"] == "2":
                break
            _time.sleep(0.05)
        assert meta["model_version_status"][0]["version"] == "2"
        out = call(":predict", {"instances": [[1, 10]]})
        np.testing.assert_allclose(out["predictions"], [[5.0, 50.0]])
    finally:
        server.shutdown()
        server.server_close()


def test_embedding_lookup_large_table_is_o_batch(tmp_path):
    """100k-row table: lookups must use the index built once in
    __init__, not rebuild an O(table) dict per call (VERDICT r3 #7)."""
    import time

    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    n = 100_000
    rng = np.random.RandomState(0)
    ids = rng.permutation(n * 2)[:n]  # unsorted, sparse id space
    values = rng.randn(n, 8).astype(np.float32)
    export_servable(
        str(tmp_path / "e"),
        lambda p, x: x * p["s"],
        {"s": np.float32(1.0)},
        np.zeros((2, 3), np.float32),
        embeddings={"items": (ids, values)},
        platforms=("cpu",),
    )
    model = load_servable(str(tmp_path / "e"))
    query = np.concatenate([ids[:64], [n * 2 + 7]])  # 64 hits + 1 miss
    t0 = time.perf_counter()
    for _ in range(100):
        rows = model.lookup_embedding("items", query)
    per_call = (time.perf_counter() - t0) / 100
    np.testing.assert_allclose(rows[:64], values[:64])
    np.testing.assert_array_equal(rows[64], np.zeros(8, np.float32))
    # The old dict-rebuild path costs ~30ms/call at 100k rows; the
    # searchsorted path is far under 5ms even on a loaded CI box.
    assert per_call < 0.005, "lookup is O(table): %.1f ms" % (
        per_call * 1e3)


def test_int8_quantized_export_roundtrip(tmp_path):
    """quantize='int8': weights-only per-channel int8 — ~4x smaller
    model.npz, loader dequantizes, predictions within quantization
    noise of the f32 export; small arrays ride through exact."""
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    rng = np.random.RandomState(0)
    params = {"w": rng.randn(256, 128).astype(np.float32),
              "b": rng.randn(128).astype(np.float32)}

    def apply_fn(p, x):
        return x @ p["w"] + p["b"]

    x = rng.randn(4, 256).astype(np.float32)
    for sub, quantize in (("f32", None), ("q8", "int8")):
        manifest = export_servable(
            str(tmp_path / sub), apply_fn, params,
            np.zeros((1, 256), np.float32), platforms=("cpu",),
            quantize=quantize,
        )
        if quantize:
            assert manifest["quantized_int8"] == ["w"]

    size_f32 = os.path.getsize(str(tmp_path / "f32" / "model.npz"))
    size_q8 = os.path.getsize(str(tmp_path / "q8" / "model.npz"))
    assert size_q8 < 0.35 * size_f32, (size_q8, size_f32)

    full = load_servable(str(tmp_path / "f32"))
    quant = load_servable(str(tmp_path / "q8"))
    np.testing.assert_array_equal(quant.params["b"], params["b"])
    want = np.asarray(full.predict(x))
    got = np.asarray(quant.predict(x))
    # Weight rounding ~scale/2 ~= max|w|/254 per element accumulates
    # ~sqrt(256)x over the length-256 dot: expect |err| well under 1
    # on outputs of magnitude ~10-30 (rtol alone would fail on the
    # near-zero outputs).
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.6)
    assert np.abs(got - want).max() > 1e-4  # it really quantized


def test_loader_rejects_unknown_feature_prefix(tmp_path):
    """A future feature prefix this loader copy doesn't understand
    must fail at LOAD time, not deep inside predict."""
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    export_servable(
        str(tmp_path / "e"), lambda p, x: x * p["s"],
        {"s": np.float32(2.0)}, np.zeros((1, 2), np.float32),
        platforms=("cpu",),
    )
    manifest_path = str(tmp_path / "e" / "manifest.json")
    with open(manifest_path) as f:
        manifest = json.load(f)
    manifest["format"] = "int4-weights+" + manifest["format"]
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="known feature prefixes"):
        load_servable(str(tmp_path / "e"))


def test_int8_quantized_embedding_tables(tmp_path):
    """quantize='int8' also covers embedding tables (the dominant CTR
    artifact): per-row int8 storage, transparent dequant in BOTH
    loaders, lookups within rounding noise; tiny tables ride through
    exact."""
    from elasticdl_tpu.models.callbacks import load_export
    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.loader import load_servable

    rng = np.random.RandomState(0)
    big_vals = rng.randn(1024, 16).astype(np.float32)
    small_vals = rng.randn(3, 4).astype(np.float32)
    manifest = export_servable(
        str(tmp_path / "e"),
        lambda p, x: x * p["s"],
        {"s": np.float32(1.0)},
        np.zeros((2, 3), np.float32),
        embeddings={
            "items": (np.arange(1024), big_vals),
            "tiny": (np.array([5, 9, 11]), small_vals),
        },
        platforms=("cpu",), quantize="int8",
    )
    assert manifest["quantized_int8"] == ["emb:items"]
    model = load_servable(str(tmp_path / "e"))
    rows = model.lookup_embedding("items", [0, 7, 1023])
    np.testing.assert_allclose(
        rows, big_vals[[0, 7, 1023]], rtol=0.02, atol=0.05)
    np.testing.assert_array_equal(
        model.lookup_embedding("tiny", [9]), small_vals[[1]])
    # load_export (the training-side loader) dequantizes too
    _, embeddings = load_export(str(tmp_path / "e"))
    np.testing.assert_allclose(
        embeddings["items"][1], big_vals, rtol=0.02, atol=0.05)


def test_generate_servable_over_http(tmp_path):
    """LLM decode serving: export_generate compiles the batched
    prefill + KV-cache decode loop INTO the servable; the stock HTTP
    server then serves token generation via :predict with zero model
    code — and the artifact is token-exact against library-side
    generate."""
    import json as _json
    import threading
    import urllib.request

    import jax

    from elasticdl_tpu.models import transformer as tfm
    from elasticdl_tpu.serving.server import ModelEndpoint, build_server

    cfg = tfm.TransformerConfig(
        vocab_size=128, dim=32, num_heads=4, num_layers=2,
        max_seq_len=32, dtype="float32")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    manifest = tfm.export_generate(
        str(tmp_path / "gen"), params, cfg, max_new_tokens=6,
        prompt_len=8, model_name="lm", platforms=("cpu",))
    assert manifest["polymorphic_batch"] is True
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        tfm.export_generate(str(tmp_path / "bad"), params, cfg,
                            max_new_tokens=30, prompt_len=8)

    server = build_server(ModelEndpoint(str(tmp_path / "gen")), port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    prompt = np.arange(16, dtype=np.int32).reshape(2, 8) % 128
    try:
        req = urllib.request.Request(
            "http://127.0.0.1:%d/v1/models/lm:predict" % port,
            data=_json.dumps({"instances": prompt.tolist()}).encode())
        with urllib.request.urlopen(req, timeout=60) as resp:
            out = np.asarray(_json.loads(resp.read())["predictions"])
        assert out.shape == (2, 14)
        want = np.asarray(tfm.generate(params, cfg, prompt,
                                       max_new_tokens=6))
        np.testing.assert_array_equal(out, want)
    finally:
        server.shutdown()
        server.server_close()


def test_sampled_generate_servable(tmp_path):
    """temperature > 0 exports a SAMPLING servable with a per-request
    seed: equal seeds reproduce exactly, different seeds diverge, and
    everything stays in-vocab past the prompt."""
    import jax

    from elasticdl_tpu.models import transformer as tfm
    from elasticdl_tpu.serving.loader import load_servable

    cfg = tfm.TransformerConfig(
        vocab_size=128, dim=32, num_heads=4, num_layers=2,
        max_seq_len=32, dtype="float32")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tfm.export_generate(
        str(tmp_path / "s"), params, cfg, max_new_tokens=6,
        prompt_len=4, temperature=0.9, platforms=("cpu",))
    model = load_servable(str(tmp_path / "s"))
    prompt = np.arange(8, dtype=np.int32).reshape(2, 4)
    one = np.asarray(model.predict(
        {"prompt": prompt, "seed": np.int32(7)}))
    same = np.asarray(model.predict(
        {"prompt": prompt, "seed": np.int32(7)}))
    other = np.asarray(model.predict(
        {"prompt": prompt, "seed": np.int32(8)}))
    np.testing.assert_array_equal(one, same)  # seed reproduces
    assert not np.array_equal(one, other)     # seed matters
    assert one.shape == (2, 10)
    np.testing.assert_array_equal(one[:, :4], prompt)
    assert ((one >= 0) & (one < 128)).all()


def test_export_generate_rejects_negative_temperature(tmp_path):
    import jax

    from elasticdl_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, dim=16, num_heads=2,
                                num_layers=1, max_seq_len=16,
                                dtype="float32")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="temperature"):
        tfm.export_generate(str(tmp_path / "t"), params, cfg,
                            max_new_tokens=4, prompt_len=4,
                            temperature=-0.5)


def test_multi_model_server(tmp_path):
    """One server process hosts several models (the TF-Serving
    model-config role): each under its own /v1/models/<name> tree,
    unknown names 404 listing the hosted set."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.server import ModelEndpoint, build_server

    for name, scale in (("a", 2.0), ("b", 5.0)):
        export_servable(
            str(tmp_path / name), lambda p, x: x * p["s"],
            {"s": np.float32(scale)}, np.zeros((1, 2), np.float32),
            model_name=name, platforms=("cpu",))
    server = build_server(
        [ModelEndpoint(str(tmp_path / "a")),
         ModelEndpoint(str(tmp_path / "b"))], port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    def predict(name, x):
        req = urllib.request.Request(
            "http://127.0.0.1:%d/v1/models/%s:predict" % (port, name),
            data=_json.dumps({"instances": x}).encode())
        with urllib.request.urlopen(req, timeout=30) as resp:
            return _json.loads(resp.read())["predictions"]

    try:
        with urllib.request.urlopen(
            "http://127.0.0.1:%d/healthz" % port, timeout=10
        ) as resp:
            assert _json.loads(resp.read()) == {"status": "ok"}
        np.testing.assert_allclose(predict("a", [[1, 2]]), [[2., 4.]])
        np.testing.assert_allclose(predict("b", [[1, 2]]), [[5., 10.]])
        with pytest.raises(urllib.error.HTTPError) as err:
            predict("c", [[1, 2]])
        assert err.value.code == 404
        with pytest.raises(ValueError, match="duplicate"):
            build_server([ModelEndpoint(str(tmp_path / "a")),
                          ModelEndpoint(str(tmp_path / "a"))], port=0)
    finally:
        server.shutdown()
        server.server_close()


def test_hot_reload_under_concurrent_load(tmp_path):
    """Hammer :predict from N threads while new versions export
    concurrently: every response must be valid and correspond to SOME
    exported version (the atomic (model, dtypes) swap under the reload
    lock must never produce a torn or failed response)."""
    import json as _json
    import threading
    import urllib.request

    from elasticdl_tpu.serving.export import export_servable
    from elasticdl_tpu.serving.server import ModelEndpoint, build_server

    base = str(tmp_path / "m")
    scales = {v: float(v) for v in range(1, 6)}

    def put(version):
        export_servable(
            os.path.join(base, str(version)),
            lambda p, x: x * p["s"],
            {"s": np.float32(scales[version])},
            np.zeros((1, 2), np.float32),
            model_name="hot", version=version, platforms=("cpu",))

    put(1)
    server = build_server(
        ModelEndpoint(base, poll_interval=0.01), port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = "http://127.0.0.1:%d/v1/models/hot:predict" % port
    stop = threading.Event()
    failures = []
    seen_scales = set()

    def hammer():
        while not stop.is_set():
            try:
                req = urllib.request.Request(
                    url, data=_json.dumps(
                        {"instances": [[1.0, 1.0]]}).encode())
                with urllib.request.urlopen(req, timeout=30) as resp:
                    out = _json.loads(resp.read())["predictions"]
                scale = out[0][0]
                if out[0] != [scale, scale] or (
                    scale not in scales.values()
                ):
                    failures.append(out)
                seen_scales.add(scale)
            except Exception as e:  # noqa: BLE001
                failures.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    try:
        for t in threads:
            t.start()
        for version in range(2, 6):
            put(version)
            import time as _time

            _time.sleep(0.3)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        server.shutdown()
        server.server_close()
    assert not failures, failures[:5]
    assert 5.0 in seen_scales  # the last version was eventually served
    assert len(seen_scales) >= 2  # at least one live flip observed
