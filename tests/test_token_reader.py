"""Binary token-file reader (data/token_reader.py): the LM-native
data path — memory-mapped fixed windows, exact sharding, e2e through
the managed master with the flagship LM."""

import os
import subprocess
import sys

import numpy as np
import pytest

from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.data.token_reader import (
    TokenFileDataReader,
    write_token_file,
)


def _make_file(path, n_tokens, vocab=500, dtype=np.uint16):
    rng = np.random.RandomState(0)
    toks = rng.randint(0, vocab, size=n_tokens)
    write_token_file(path, toks, dtype=dtype)
    return toks


def test_windows_shards_and_contents(tmp_path):
    path = str(tmp_path / "train.bin")
    toks = _make_file(path, n_tokens=16 * 10 + 7)  # trailing partial
    reader = TokenFileDataReader(path, seq_len=16, records_per_shard=4)
    shards = reader.create_shards()
    # 10 full windows (partial dropped) in shards of 4/4/2
    assert [(s[1], s[2]) for s in shards] == [(0, 4), (4, 8), (8, 10)]

    class T:
        class shard:
            start, end = 4, 8
            record_indices = None

    got = list(reader.read_records(T))
    assert len(got) == 4
    for k, (rec,) in enumerate(got):
        assert rec.dtype == np.int32
        np.testing.assert_array_equal(
            rec, toks[(4 + k) * 16:(5 + k) * 16])


def test_append_and_dtype_guard(tmp_path):
    path = str(tmp_path / "t.bin")
    write_token_file(path, [1, 2, 3])
    write_token_file(path, [4, 5])  # append
    reader = TokenFileDataReader(path, seq_len=5)
    assert reader.create_shards() == [(path, 0, 1)]
    with pytest.raises(ValueError):
        write_token_file(path, [70000])  # > uint16


def test_factory_origin(tmp_path):
    path = str(tmp_path / "d.bin")
    _make_file(path, 64, dtype=np.uint32)
    reader = create_data_reader("tokens:%s:8:uint32" % path,
                                records_per_shard=4)
    assert isinstance(reader, TokenFileDataReader)
    assert reader.create_shards() == [(path, 0, 4), (path, 4, 8)]
    with pytest.raises(ValueError):
        create_data_reader("tokens:%s" % path)


@pytest.mark.slow
def test_managed_lm_training_from_token_file(tmp_path):
    """e2e: tokenize -> write_token_file -> managed LM training job
    through the master CLI (the GPT-style pretraining loop)."""
    path = str(tmp_path / "corpus.bin")
    _make_file(path, n_tokens=16 * 256, vocab=128)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ELASTICDL_TPU_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable, "-m", "elasticdl_tpu.master.main",
            "--model_zoo", "transformer",
            "--model_params",
            "vocab_size=128;dim=32;num_heads=4;num_layers=2;"
            "seq_len=16;dtype=float32",
            "--data_origin", "tokens:%s:16" % path,
            "--batch_size", "16", "--num_workers", "1",
            "--num_minibatches_per_task", "4",
            "--shuffle", "true",  # record_indices through the REAL
            # task manager, not just the unit-test fake
        ],
        capture_output=True, text=True, env=env, timeout=300,
    )
    text = proc.stdout + proc.stderr
    assert proc.returncode == 0, text[-3000:]
    assert "job finished" in text
    assert "'failed': {0: 0" in text, text[-2000:]


def test_reader_honors_shuffle_permutation(tmp_path):
    """record_indices (the task manager's shuffle permutation) must
    drive the read order — not the linear range (advisor catch)."""
    path = str(tmp_path / "s.bin")
    toks = _make_file(path, n_tokens=16 * 6)
    reader = TokenFileDataReader(path, seq_len=16, records_per_shard=6)

    class Shard:
        start, end = 0, 6
        record_indices = [5, 2, 0]

    class T:
        shard = Shard

    got = [rec for (rec,) in reader.read_records(T)]
    assert len(got) == 3
    for rec, idx in zip(got, [5, 2, 0]):
        np.testing.assert_array_equal(
            rec, toks[idx * 16:(idx + 1) * 16])
    write_token_file(path, [])  # empty append is a no-op
    assert os.path.getsize(path) == 16 * 6 * 2


def test_dtype_sidecar_guards_appends_and_reads(tmp_path):
    """Headerless format + mixed dtypes would silently corrupt: the
    .meta sidecar records the creation dtype, mismatched appends and
    readers fail loudly, and the factory rejects non-integer dtypes."""
    path = str(tmp_path / "m.bin")
    write_token_file(path, [1, 2, 3, 4])  # uint16 recorded
    with pytest.raises(ValueError, match="would corrupt"):
        write_token_file(path, [5], dtype=np.uint32)
    with pytest.raises(ValueError, match="sidecar"):
        TokenFileDataReader(path, seq_len=2, dtype=np.uint32)
    with pytest.raises(ValueError, match="uint16 or uint32"):
        create_data_reader("tokens:%s:2:float32" % path)
    # matching dtype still appends fine
    write_token_file(path, [5, 6])
    assert TokenFileDataReader(path, seq_len=2).create_shards() == [
        (path, 0, 3)]


def test_truncated_or_stale_shard_fails_loudly(tmp_path):
    """A shard range beyond the file's real length must raise a clear
    error, not silently yield short windows that break the static
    [B, T] batch shape downstream (ADVICE r5 low)."""
    path = str(tmp_path / "trunc.bin")
    _make_file(path, n_tokens=16 * 10)
    reader = TokenFileDataReader(path, seq_len=16, records_per_shard=4)
    # Warm the mmap on the full file, then truncate it underneath the
    # reader — the stale-shard / truncated-file scenario.
    class T:
        class shard:
            start, end = 8, 10
            record_indices = None

    assert len(list(reader.read_records(T))) == 2
    with open(path, "r+b") as f:
        f.truncate(16 * 9 * 2)  # drop the last uint16 window
    reader2 = TokenFileDataReader(path, seq_len=16, records_per_shard=4)
    with pytest.raises(ValueError, match="truncated|stale"):
        list(reader2.read_records(T))


def test_shuffle_indices_out_of_range_fail_loudly(tmp_path):
    """Stale resume metadata (record_indices from a longer file) hits
    the same bounds check."""
    path = str(tmp_path / "stale.bin")
    _make_file(path, n_tokens=16 * 4)
    reader = TokenFileDataReader(path, seq_len=16)

    class T:
        class shard:
            start, end = 0, 2
            record_indices = [1, 99]  # 99 is beyond the 4 windows

    with pytest.raises(ValueError, match="out of range"):
        list(reader.read_records(T))
