"""Fleet-scale serving tier (serving/router.py, serving/fleet.py):
rendezvous-hash stability, retry-once failover on an ejected replica,
the no-mixed-version hot-swap barrier, graceful SIGTERM drain, the
rejoin-cannot-regress rule, and the Prometheus /metrics surface."""

import http.client
import json
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.master.status_server import (
    fleet_to_prometheus,
    serving_to_prometheus,
)
from elasticdl_tpu.serving.batcher import BatchConfig
from elasticdl_tpu.serving.export import export_servable
from elasticdl_tpu.serving.fleet import (
    FleetCoordinator,
    FleetState,
    HealthProber,
    _statz_view,
)
from elasticdl_tpu.serving.router import (
    AdmissionGate,
    Router,
    build_router_server,
    pick_replica,
    rendezvous_rank,
)
from elasticdl_tpu.serving.server import (
    DrainController,
    ModelEndpoint,
    build_server,
    install_drain_handler,
)
from elasticdl_tpu.utils.args import build_router_parser

W = np.arange(8, dtype=np.float32).reshape(4, 2)


def _export_version(base, version, bias=0.0):
    export_servable(
        os.path.join(str(base), str(version)),
        lambda p, x: x @ p["w"] + bias, {"w": W},
        np.zeros((1, 4), np.float32), model_name="lin",
        version=version, platforms=("cpu",),
    )


class _Replica:
    """One in-process fleet-managed model server."""

    def __init__(self, base, **endpoint_kwargs):
        endpoint_kwargs.setdefault("fleet_managed", True)
        self.endpoint = ModelEndpoint(str(base), **endpoint_kwargs)
        self.server = build_server(self.endpoint, port=0)
        self.addr = "127.0.0.1:%d" % self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def kill(self):
        """Close the LISTENING socket so new connections are refused —
        the observable signature of a dead replica process."""
        self.server.shutdown()
        self.server.server_close()

    def close(self):
        self.kill()
        self.endpoint.close()


def _dead_addr():
    """A port that actively refuses connections."""
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    return "127.0.0.1:%d" % port


def _post(port, path, payload, timeout=30):
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(payload))
        resp = conn.getresponse()
        raw = resp.read()
        return resp.status, (json.loads(raw) if raw else None)
    finally:
        conn.close()


def _build_router(replica_addrs, base="", **kw):
    kw.setdefault("probe_interval", 0.05)
    kw.setdefault("probe_timeout", 2.0)
    kw.setdefault("poll_interval", 0.1)
    return Router(replica_addrs, export_dir=str(base), **kw)


def _wait(predicate, timeout=15, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


# -- rendezvous hashing ------------------------------------------------


def test_rendezvous_removal_moves_only_the_lost_keyspace():
    """Removing a replica re-homes ONLY its own keys (each to its
    second choice); every other key keeps its replica."""
    addrs = ["r%d:80" % i for i in range(4)]
    keys = ["key-%d" % i for i in range(1000)]
    before = {k: pick_replica(k, addrs) for k in keys}
    removed = addrs[1]
    survivors = [a for a in addrs if a != removed]
    moved = 0
    for k in keys:
        after = pick_replica(k, survivors)
        if before[k] == removed:
            moved += 1
            # Failover lands on the key's SECOND rendezvous choice.
            assert after == rendezvous_rank(k, addrs)[1]
        else:
            assert after == before[k], k
    # ~1/N of the keyspace lived on the removed replica.
    assert 150 < moved < 350, moved


def test_rendezvous_addition_steals_about_one_nth():
    addrs = ["r%d:80" % i for i in range(4)]
    keys = ["key-%d" % i for i in range(1000)]
    before = {k: pick_replica(k, addrs) for k in keys}
    grown = addrs + ["r-new:80"]
    moved = sum(1 for k in keys if pick_replica(k, grown) != before[k])
    # Expected 1/5 = 200; generous bounds against hash variance.
    assert 120 < moved < 300, moved
    # Every moved key moved TO the new replica, never between old ones.
    for k in keys:
        after = pick_replica(k, grown)
        assert after == before[k] or after == "r-new:80"


def test_statz_view_takes_min_version_across_models():
    version, occupancy, wait_ms, recent_ms, draining = _statz_view({
        "draining": False,
        "models": {
            "a": {"version": 7, "mean_batch_occupancy": 3.0,
                  "queue_wait_recent_ms": 1.5,
                  "timing": {"batcher.queue_wait":
                             {"mean_s": 0.002, "count": 5}}},
            "b": {"version": 5, "mean_batch_occupancy": None,
                  "timing": {}},
        },
    })
    assert version == 5  # the barrier must hold for EVERY model
    assert occupancy == 3.0
    assert wait_ms == pytest.approx(2.0)
    assert recent_ms == pytest.approx(1.5)
    assert draining is False


# -- admission gate ----------------------------------------------------


def test_admission_gate_drains_before_reopening():
    gate = AdmissionGate()
    assert gate.enter(timeout=1)
    gate.close()
    # New entries are refused while closed...
    assert not gate.enter(timeout=0.05)
    # ...and the barrier waits for the in-flight one.
    assert not gate.wait_idle(timeout=0.05)
    gate.exit_()
    assert gate.wait_idle(timeout=1)
    gate.open()
    assert gate.enter(timeout=1)
    gate.exit_()


# -- routing + failover ------------------------------------------------


def test_router_routes_and_ejects_dead_replica_with_one_retry(
        tmp_path):
    """A replica that dies after passing its health probe: the next
    forward routed to it fails at the socket, the router ejects it and
    retries the request on a survivor EXACTLY once — the client sees
    one 200, never an error."""
    base = tmp_path / "exports"
    _export_version(base, 1)
    alive = _Replica(base)
    doomed = _Replica(base)
    router = _build_router([alive.addr, doomed.addr], base)
    server = build_router_server(router, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        router.prober.probe_once()
        router.coordinator.tick()
        assert sorted(router.state.routable(1)) == sorted(
            [alive.addr, doomed.addr])
        # A key owned by the doomed replica, so the retry is exercised.
        key = next("k%d" % i for i in range(1000)
                   if pick_replica("k%d" % i,
                                   [alive.addr, doomed.addr])
                   == doomed.addr)
        doomed.kill()
        status, body = _post(port, "/v1/models/lin:predict",
                             {"instances": [[1, 2, 3, 4]],
                              "routing_key": key})
        assert status == 200, body
        assert body["model_version"] == 1
        replicas, counters = router.state.snapshot()
        assert counters.get("router.retried_requests") == 1
        assert replicas[doomed.addr]["healthy"] is False
        # Keyed traffic for the dead replica's keyspace now lands on
        # the survivor without any further retries.
        status, _ = _post(port, "/v1/models/lin:predict",
                          {"instances": [[1, 2, 3, 4]],
                           "routing_key": key})
        assert status == 200
        _, counters = router.state.snapshot()
        assert counters.get("router.retried_requests") == 1
    finally:
        server.shutdown()
        server.server_close()
        router.stop()
        alive.close()
        doomed.endpoint.close()


def test_routing_only_mode_serves_without_a_committed_version(
        tmp_path):
    """No --export_dir = routing-only: there is no committed version
    to pin to, so any healthy replica is routable (regression: the
    version filter used to demand serving_version == 0 and 503'd
    everything forever)."""
    base = tmp_path / "exports"
    _export_version(base, 1)
    replica = _Replica(base)
    router = _build_router([replica.addr], "")
    server = build_router_server(router, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        assert not router.coordinating
        assert router.committed_view() is None
        router.prober.probe_once()
        status, body = _post(port, "/v1/models/lin:predict",
                             {"instances": [[1, 2, 3, 4]]})
        assert status == 200, body
        assert body["model_version"] == 1
        assert router.fleet_status()["coordinating"] is False
    finally:
        server.shutdown()
        server.server_close()
        router.stop()
        replica.close()


def test_ejected_replica_rides_back_in_with_backoff_probes(tmp_path):
    base = tmp_path / "exports"
    _export_version(base, 1)
    replica = _Replica(base)
    state = FleetState([replica.addr, _dead_addr()],
                       probe_interval=0.05)
    prober = HealthProber(state, probe_timeout=1.0)
    prober.probe_once()
    replicas, _ = state.snapshot()
    assert replicas[replica.addr]["healthy"] is True
    dead = next(a for a in replicas if a != replica.addr)
    assert replicas[dead]["healthy"] is False
    # The dead replica's next probe is pushed out by the jittered
    # backoff — strictly beyond the healthy cadence after a few misses.
    for _ in range(4):
        state.note_probe_failure(dead, time.monotonic())
    with state._lock:
        healthy_next = state._replicas[replica.addr].next_probe_at
        dead_next = state._replicas[dead].next_probe_at
    assert dead_next > healthy_next
    replica.close()


# -- fleet hot-swap ----------------------------------------------------


def test_version_flip_mid_storm_never_mixes_versions(tmp_path):
    """The acceptance drill in miniature: closed-loop keyed clients
    hammer the router while a new export version rolls out.  Every
    response is a 200, and no key EVER observes a version regression
    (new then old) — the barrier drains stale requests, it never mixes
    them."""
    base = tmp_path / "exports"
    _export_version(base, 1)
    fleet = [_Replica(base) for _ in range(2)]
    router = _build_router([r.addr for r in fleet], base,
                           barrier_timeout=30.0)
    server = build_router_server(router, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    router.start(coordinate=True)
    try:
        assert _wait(lambda:
                     router.coordinator.committed_version == 1)
        errors = []
        observed = {}  # key -> [version, ...]
        stop = threading.Event()

        def client(idx):
            key = "storm-%d" % idx
            seen = observed.setdefault(key, [])
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            body = json.dumps({"instances": [[1, 2, 3, 4]],
                               "routing_key": key})
            try:
                while not stop.is_set():
                    conn.request("POST", "/v1/models/lin:predict",
                                 body=body)
                    resp = conn.getresponse()
                    raw = resp.read()
                    if resp.status != 200:
                        errors.append((resp.status, raw[:200]))
                        return
                    seen.append(json.loads(raw)["model_version"])
            except Exception as e:  # noqa: BLE001 — a dropped request
                # IS the failure this test exists to catch
                errors.append(repr(e))
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(4)]
        for t in threads:
            t.start()
        # Fire the hot-swap mid-storm.
        time.sleep(0.3)
        _export_version(base, 2, bias=1.0)
        assert _wait(lambda:
                     router.coordinator.committed_version == 2)
        time.sleep(0.3)  # keep storming past the flip
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors[:3]
        flipped = 0
        for key, versions in observed.items():
            assert versions, key
            # Monotone non-decreasing: never v2 then v1 for one key.
            assert versions == sorted(versions), (key, versions)
            if versions[0] == 1 and versions[-1] == 2:
                flipped += 1
        assert flipped, observed  # the storm really straddled the flip
    finally:
        router.stop()
        server.shutdown()
        server.server_close()
        for r in fleet:
            r.close()


def test_rejoining_replica_heals_to_committed_never_regresses(
        tmp_path):
    """ISSUE satellite: loader polling and the version barrier must
    agree after a replica restarts mid-rollout.  The rejoiner booted
    while only version 1 was complete on its disk, so it serves 1; the
    fleet meanwhile committed 2.  It must NOT be routable at 1, its
    target must be seeded by the COORDINATOR (prepare+commit up to the
    committed version), and the replica-side commit_version must refuse
    any regression — so the fleet's committed version can never move
    backwards off a rejoiner's local disk scan."""
    base = tmp_path / "exports"
    _export_version(base, 1)
    rejoiner = _Replica(base)          # boots while only v1 exists
    assert rejoiner.endpoint.serving_version() == 1
    _export_version(base, 2, bias=1.0)
    veteran = _Replica(base)           # boots after v2 landed
    assert veteran.endpoint.serving_version() == 2
    router = _build_router([veteran.addr, rejoiner.addr], base,
                           barrier_timeout=30.0)
    try:
        router.prober.probe_once()
        assert router.coordinator.seed_committed()
        # Committed adopts the fleet MAX (what the fleet last agreed
        # on), never the rejoiner's older disk state.
        assert router.coordinator.committed_version == 2
        # Not routable while lagging: the flip stays atomic per key.
        assert router.state.routable(2) == [veteran.addr]

        def healed():
            router.prober.probe_once()
            router.coordinator.tick()
            return rejoiner.endpoint.serving_version() == 2

        assert _wait(healed, timeout=30, interval=0.1)
        router.prober.probe_once()
        assert sorted(router.state.routable(2)) == sorted(
            [veteran.addr, rejoiner.addr])
        # Replica-side regression guard, independent of the router.
        refused = rejoiner.endpoint.commit_version(1)
        assert refused["committed"] is False
        assert "regress" in refused["error"]
        # Fleet-managed replicas never self-swap off a disk scan.
        rejoiner.endpoint.maybe_reload()
        assert rejoiner.endpoint.serving_version() == 2
    finally:
        router.stop()
        veteran.close()
        rejoiner.close()


# -- graceful drain ----------------------------------------------------


def test_sigterm_drains_then_stops(tmp_path):
    """SIGTERM mid-traffic: every in-flight/admitted request completes
    (200), later requests get 503 + Connection: close, the health
    probe fails so a router would eject the replica, and the server
    then stops on its own — no dropped connections at any point."""
    base = tmp_path / "exports"
    _export_version(base, 1)
    endpoint = ModelEndpoint(
        str(base), batching=BatchConfig(max_batch_size=4,
                                        batch_timeout_ms=20.0,
                                        warm=False))
    server = build_server(endpoint, port=0)
    port = server.server_address[1]
    serve_thread = threading.Thread(target=server.serve_forever,
                                    daemon=True)
    serve_thread.start()
    old_handler = signal.getsignal(signal.SIGTERM)
    install_drain_handler(server, [endpoint], server.drain,
                          grace_secs=20.0)
    statuses = []
    lock = threading.Lock()

    def client():
        for _ in range(300):
            try:
                status, body = _post(
                    port, "/v1/models/lin:predict",
                    {"instances": [[1, 2, 3, 4]]}, timeout=30)
            except (ConnectionRefusedError, ConnectionResetError):
                # Clean post-shutdown refusal: the drained server
                # closed its listening socket (a connect racing the
                # close gets RST from the kernel backlog) — instantly
                # retryable against another replica, never a hung or
                # half-answered ADMITTED request (those are counted
                # in-flight and drained before the socket closes).
                with lock:
                    statuses.append("refused")
                return
            except OSError as e:
                with lock:
                    statuses.append(repr(e))
                return
            with lock:
                statuses.append(status)
            if status != 200:
                return

    try:
        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.15)
        os.kill(os.getpid(), signal.SIGTERM)
        for t in threads:
            t.join(timeout=30)
        # The server shuts itself down once drained.
        assert _wait(lambda: not serve_thread.is_alive(), timeout=20)
        with lock:
            seen = list(statuses)
        assert seen and set(seen) <= {200, 503, "refused"}, seen[:10]
        assert 200 in seen
    finally:
        signal.signal(signal.SIGTERM, old_handler)
    # Refusal semantics directly on the controller.
    drain = DrainController()
    drain.begin()
    assert drain.admit() is False
    assert drain.wait_idle(0.1) is True


def test_drain_refusal_carries_connection_close(tmp_path):
    base = tmp_path / "exports"
    _export_version(base, 1)
    endpoint = ModelEndpoint(str(base))
    server = build_server(endpoint, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        server.drain.begin()
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=10)
        conn.request("POST", "/v1/models/lin:predict",
                     body=json.dumps({"instances": [[1, 2, 3, 4]]}))
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 503
        assert resp.getheader("Connection") == "close"
        conn.close()
        # The health probe fails too, so the router ejects us.
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=10)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 503
        conn.close()
        # /statz still answers (draining: true) for observability.
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=10)
        conn.request("GET", "/statz")
        resp = conn.getresponse()
        statz = json.loads(resp.read())
        assert statz["draining"] is True
        conn.close()
    finally:
        server.shutdown()
        server.server_close()
        endpoint.close()


# -- observability -----------------------------------------------------


def test_metrics_exposition_formats(tmp_path):
    base = tmp_path / "exports"
    _export_version(base, 3)
    endpoint = ModelEndpoint(str(base))
    server = build_server(endpoint, port=0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        status, _ = _post(port, "/v1/models/lin:predict",
                          {"instances": [[1, 2, 3, 4]]})
        assert status == 200
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=10)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.getheader("Content-Type").startswith("text/plain")
        conn.close()
        assert 'elasticdl_serving_version{model="lin"} 3' in body
        assert "elasticdl_serving_draining 0" in body
        # Router-side renderer over a synthetic fleet status.
        text = fleet_to_prometheus({
            "committed_version": 3,
            "replicas": {"a:1": {"healthy": True,
                                 "serving_version": 3,
                                 "inflight": 2,
                                 "queue_wait_ms": 1.5}},
            "counters": {"router.forwarded": 9},
        })
        assert "elasticdl_fleet_committed_version 3" in text
        assert ('elasticdl_fleet_replica_serving_version{replica='
                '"a:1"} 3') in text
        assert ('elasticdl_fleet_router_counter{name='
                '"router.forwarded"} 9') in text
        # Serving renderer includes the cache gauges when present.
        text = serving_to_prometheus({
            "draining": False,
            "models": {"lin": {
                "version": 3,
                "counters": {"batcher.requests": 4,
                             "batcher.batches": 2},
                "mean_batch_occupancy": 2.0,
                "timing": {"batcher.queue_wait":
                           {"mean_s": 0.001, "count": 4}},
                "emb_cache": {"bytes": 128, "rows": 2,
                              "evicted_rows": 1, "hits": 6,
                              "misses": 2, "hit_ratio": 0.75},
            }},
        })
        assert 'elasticdl_serving_occupancy{model="lin"} 2.0' in text
        assert ('elasticdl_serving_emb_cache_hit_ratio{model="lin"} '
                '0.75') in text
        assert ('elasticdl_serving_queue_wait_ms{model="lin"} 1.0'
                in text)
    finally:
        server.shutdown()
        server.server_close()
        endpoint.close()


def test_router_parser_roundtrip():
    args = build_router_parser().parse_args(
        ["--replicas", "a:1,b:2", "--export_dir", "/tmp/x",
         "--probe_interval", "0.25"])
    assert args.replicas == "a:1,b:2"
    assert args.probe_interval == 0.25
    assert args.barrier_timeout == 120.0
    with pytest.raises(SystemExit):
        build_router_parser().parse_args([])


def test_coordinator_seeds_from_replicas_not_disk(tmp_path):
    """The committed version adopts what the fleet actually serves (the
    max across healthy replicas), falling back to the export scan only
    when no replica has ever been probed."""
    base = tmp_path / "exports"
    _export_version(base, 1)
    _export_version(base, 2)
    state = FleetState(["a:1"], probe_interval=0.05)
    coordinator = FleetCoordinator(state, str(base))
    state.note_probe_ok("a:1", {"models": {"lin": {"version": 1}}},
                        time.monotonic())
    assert coordinator.seed_committed()
    assert coordinator.committed_version == 1  # NOT the disk's 2
    # Unprobed fleet: disk scan fallback.
    coordinator2 = FleetCoordinator(
        FleetState(["b:1"], probe_interval=0.05), str(base))
    assert coordinator2.seed_committed()
    assert coordinator2.committed_version == 2
