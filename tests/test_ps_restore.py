"""PS relaunch-with-restore: slot tables and optimizer state survive
(the fault-tolerance path PSManager exercises)."""

import numpy as np

from elasticdl_tpu.ps.server import ParameterServer
from elasticdl_tpu.utils.args import parse_ps_args
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.utils.grpc_utils import build_channel, wait_for_channel_ready
from elasticdl_tpu.worker.ps_client import PSClient


def make_ps(tmp_path, restore=False):
    argv = [
        "--port", "0", "--ps_id", "0", "--num_ps", "1",
        "--opt_type", "adam", "--opt_args", "learning_rate=0.01",
        "--checkpoint_dir", str(tmp_path), "--checkpoint_steps", "1",
    ]
    if restore:
        argv += ["--checkpoint_dir_for_init", str(tmp_path)]
    ps = ParameterServer(parse_ps_args(argv))
    ps.prepare()
    channel = build_channel("localhost:%d" % ps.port)
    wait_for_channel_ready(channel)
    return ps, PSClient([channel])


def test_relaunched_adam_ps_applies_sparse_pushes(tmp_path):
    ps1, client1 = make_ps(tmp_path)
    infos = [{"name": "emb", "dim": 2, "initializer": "zeros"}]
    client1.push_model({"w": np.ones(2, np.float32)},
                       embedding_infos=infos)
    client1.push_gradients(
        {"w": np.ones(2, np.float32)},
        {"emb": (np.ones((1, 2), np.float32), np.array([3], np.int64))},
        version=0,
    )
    emb_before = client1.pull_embedding_vectors("emb", [3])
    ps1.stop()

    ps2, client2 = make_ps(tmp_path, restore=True)
    try:
        assert ps2.parameters.initialized
        assert ps2.parameters.version == 1
        # Restart-generation fencing (docs/ps_recovery.md): the second
        # incarnation serves a strictly newer generation, and the
        # restored label seeds its durable mark (the commit mark must
        # not drop to 0 on relaunch).
        assert ps2.generation == 2
        assert ps2.servicer.durable_version == 1
        # restored embedding row matches
        np.testing.assert_allclose(
            client2.pull_embedding_vectors("emb", [3]), emb_before
        )
        # adam slot tables restored: m for id 3 must be non-zero
        m_table = ps2.parameters.slot_tables["emb-m"]
        assert not np.allclose(m_table.get([3]), 0.0)
        # the critical regression: a sparse push after restore must apply
        accepted, version = client2.push_gradients(
            {"w": np.ones(2, np.float32)},
            {"emb": (np.ones((1, 2), np.float32),
                     np.array([3], np.int64))},
            version=1,
        )
        assert accepted and version == 2
    finally:
        ps2.stop()


def test_restored_dense_adam_matches_uninterrupted_trajectory(tmp_path):
    """Dense Adam m/v/step survive a PS relaunch (ADVICE r1: they silently
    reset to zero): a restored shard applies the next push identically to a
    shard that never died."""
    grad0 = np.full(4, 0.5, np.float32)
    grad1 = np.full(4, -0.25, np.float32)

    # Uninterrupted trajectory.
    ps_ref, client_ref = make_ps(tmp_path / "ref")
    client_ref.push_model({"w": np.ones(4, np.float32)})
    client_ref.push_gradients({"w": grad0}, {}, version=0)
    client_ref.push_gradients({"w": grad1}, {}, version=1)
    want = ps_ref.parameters.dense["w"].copy()
    ps_ref.stop()

    # Killed-after-step-1 + restored trajectory.
    ps1, client1 = make_ps(tmp_path / "elastic")
    client1.push_model({"w": np.ones(4, np.float32)})
    client1.push_gradients({"w": grad0}, {}, version=0)
    assert ps1.optimizer.step == 1
    ps1.stop()

    ps2, client2 = make_ps(tmp_path / "elastic", restore=True)
    try:
        assert ps2.optimizer.step == 1  # step counter restored
        assert ps2.optimizer._dense_slots  # m/v restored, not reset
        client2.push_gradients({"w": grad1}, {}, version=1)
        np.testing.assert_allclose(
            ps2.parameters.dense["w"], want, rtol=1e-6
        )
    finally:
        ps2.stop()
