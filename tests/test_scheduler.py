"""Multi-tenant elastic scheduler (master/scheduler.py,
docs/scheduler.md): resize policy invariants (starvation-freedom,
min-share floors, weighted fairness, admission queueing), job-scoped
RPC routing over the shared pool, the drain-without-retry-burn shrink
path, journaled decision replay, and the decision->handover trace
link."""

import json

import pytest

from elasticdl_tpu.master.journal import JournalWriter, replay_journal
from elasticdl_tpu.master.rendezvous import RendezvousServer
from elasticdl_tpu.master.scheduler import (
    FINISHED,
    JobRegistry,
    JobSpec,
    ManagedJob,
    MultiTenantServicer,
    PENDING,
    RUNNING,
    ResizeController,
    compute_targets,
)
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.status_server import collect_multitenant_status
from elasticdl_tpu.master.task_manager import TaskManager
from elasticdl_tpu.proto import elastic_pb2 as pb
from elasticdl_tpu.utils import tracing
from elasticdl_tpu.utils.prom import multitenant_to_prometheus


def make_job(job_id, name, n_tasks=4, records_per_task=32,
             rendezvous=False, journal=None, **spec_kw):
    spec_kw.setdefault("data_origin", "synthetic_mnist:128")
    tm = TaskManager(
        training_shards=[("f", 0, records_per_task * n_tasks)],
        records_per_task=records_per_task,
    )
    if journal is not None:
        tm.attach_journal(journal, bootstrap=True)
    spec = JobSpec(name, **spec_kw)
    rdzv = (
        RendezvousServer(grace_secs=0.05, name=name)
        if rendezvous else None
    )
    servicer = MasterServicer(tm, rendezvous_server=rdzv,
                              journal=journal, job_id=job_id)
    return ManagedJob(job_id, spec, tm, servicer, rendezvous=rdzv,
                      journal=journal)


def make_cluster(jobs_kw, pool_size=4, journal=None, **controller_kw):
    """registry + controller + servicer over freshly built jobs."""
    registry = JobRegistry(journal=journal, pool_size=pool_size)
    jobs = []
    for index, kw in enumerate(jobs_kw):
        job = make_job(index + 1, **kw)
        registry.submit(job)
        jobs.append(job)
    controller = ResizeController(registry, **controller_kw)
    return registry, controller, MultiTenantServicer(registry), jobs


# -- resize policy (pure) ----------------------------------------------------

def test_targets_weighted_fair_share():
    targets = compute_targets(8, [
        {"id": 1, "min": 1, "max": 0, "weight": 3.0, "demand": 100},
        {"id": 2, "min": 1, "max": 0, "weight": 1.0, "demand": 100},
    ])
    assert targets == {1: 6, 2: 2}          # floors 1+1, surplus 6 split 3:1
    assert sum(targets.values()) == 8       # work-conserving


def test_targets_min_share_floor_beats_weight():
    # A heavy job cannot starve a light one below its floor.
    targets = compute_targets(4, [
        {"id": 1, "min": 1, "max": 0, "weight": 100.0, "demand": 100},
        {"id": 2, "min": 2, "max": 0, "weight": 0.01, "demand": 100},
    ])
    assert targets[2] >= 2
    assert sum(targets.values()) == 4


def test_targets_max_clamp_redistributes():
    targets = compute_targets(8, [
        {"id": 1, "min": 1, "max": 2, "weight": 10.0, "demand": 100},
        {"id": 2, "min": 1, "max": 0, "weight": 1.0, "demand": 100},
    ])
    assert targets == {1: 2, 2: 6}          # clamped surplus re-offered


def test_targets_demand_caps_allocation():
    # Never park more workers on a job than it has runnable tasks.
    targets = compute_targets(8, [
        {"id": 1, "min": 1, "max": 0, "weight": 1.0, "demand": 2},
        {"id": 2, "min": 1, "max": 0, "weight": 1.0, "demand": 100},
    ])
    assert targets[1] == 2
    assert targets[2] == 6


def test_targets_zero_demand_job_releases_everything():
    targets = compute_targets(4, [
        {"id": 1, "min": 2, "max": 0, "weight": 1.0, "demand": 0},
        {"id": 2, "min": 1, "max": 0, "weight": 1.0, "demand": 10},
    ])
    assert targets[1] == 0
    assert targets[2] == 4


def test_targets_starvation_freedom_on_degraded_pool():
    # Pool shrank below the sum of floors: every job with demand still
    # gets a worker before any job gets its second.
    targets = compute_targets(3, [
        {"id": 1, "min": 2, "max": 0, "weight": 5.0, "demand": 10},
        {"id": 2, "min": 2, "max": 0, "weight": 1.0, "demand": 10},
        {"id": 3, "min": 2, "max": 0, "weight": 1.0, "demand": 10},
    ])
    assert all(targets[j] >= 1 for j in (1, 2, 3))
    assert sum(targets.values()) == 3


# -- admission control -------------------------------------------------------

def test_admission_queues_job_the_pool_cannot_fit():
    registry, controller, _sv, jobs = make_cluster(
        [dict(name="a", min_workers=3),
         dict(name="b", min_workers=2)],
        pool_size=4,
    )
    assert jobs[0].state == RUNNING
    assert jobs[1].state == PENDING         # 3 + 2 > 4: queued
    assert registry.status()["pending_jobs"] == 1
    # capacity frees when job a finishes -> the queue drains FIFO
    while True:
        task = jobs[0].task_manager.get(0)
        if task is None:
            break
        jobs[0].task_manager.report(task.id, True)
    controller.tick()
    assert jobs[0].state == FINISHED
    assert jobs[1].state == RUNNING
    assert registry.status()["pending_jobs"] == 0


def test_admission_is_fifo_never_jumps_the_queue():
    registry, _ctrl, _sv, jobs = make_cluster(
        [dict(name="a", min_workers=2),
         dict(name="b", min_workers=3),     # cannot fit
         dict(name="c", min_workers=1)],    # COULD fit, but behind b
        pool_size=4,
    )
    assert [j.state for j in jobs] == [RUNNING, PENDING, PENDING]
    registry.admit_pending()
    assert [j.state for j in jobs] == [RUNNING, PENDING, PENDING]


# -- registration / routing --------------------------------------------------

def test_registration_spreads_workers_by_target_deficit():
    registry, _ctrl, sv, _jobs = make_cluster(
        [dict(name="a", n_tasks=8), dict(name="b", n_tasks=8)],
        pool_size=4,
    )
    for wid in range(4):
        sv.get_task(pb.GetTaskRequest(worker_id=wid))
    assigned = registry.status()["workers_assigned"]
    assert assigned == {"a": 2, "b": 2}


def test_handshake_carries_job_config_only_on_change():
    _reg, _ctrl, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=4)], pool_size=1,
    )
    res = sv.get_task(pb.GetTaskRequest(worker_id=0))
    assert res.job_id == jobs[0].job_id
    assert res.task.job_id == jobs[0].job_id
    cfg = json.loads(res.job_config)
    assert cfg["job"] == "a"
    assert cfg["data_origin"] == "synthetic_mnist:128"
    # steady state: same assignment echoed back -> no config payload
    res2 = sv.get_task(
        pb.GetTaskRequest(worker_id=0, job_id=res.job_id)
    )
    assert res2.job_id == jobs[0].job_id
    assert res2.job_config == ""


def test_task_ids_collide_across_jobs_and_route_by_job_id():
    # Both jobs dispatch a task with id 1: the job-scoped report must
    # complete each in ITS job, never the other's.
    _reg, _ctrl, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=2), dict(name="b", n_tasks=2)],
        pool_size=2,
    )
    r0 = sv.get_task(pb.GetTaskRequest(worker_id=0))
    r1 = sv.get_task(pb.GetTaskRequest(worker_id=1))
    assert r0.task.id == 1 and r1.task.id == 1
    assert r0.job_id != r1.job_id
    sv.report_task_result(
        pb.ReportTaskResultRequest(task_id=1, job_id=r1.job_id)
    )
    by_id = {j.job_id: j for j in jobs}
    assert by_id[r1.job_id].task_manager.counts()["completed"][
        int(pb.TRAINING)] == 1
    assert by_id[r0.job_id].task_manager.counts()["completed"][
        int(pb.TRAINING)] == 0
    # unscoped result (job_id 0) is dropped loudly, not guessed
    sv.report_task_result(pb.ReportTaskResultRequest(task_id=1))
    assert by_id[r0.job_id].task_manager.counts()["completed"][
        int(pb.TRAINING)] == 0


def test_per_job_telemetry_never_collides_on_worker_id():
    # The satellite fix: worker id 7 reports progress for BOTH jobs
    # (externally-launched pools can reuse ids); each job's aggregate
    # sees only its own series.
    _reg, _ctrl, sv, jobs = make_cluster(
        [dict(name="a"), dict(name="b")], pool_size=2,
    )
    sv.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=7, record_count=32, job_id=jobs[0].job_id,
        steps_per_sec=5.0, steps_done=10,
    ))
    sv.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=7, record_count=64, job_id=jobs[1].job_id,
        steps_per_sec=11.0, steps_done=20,
    ))
    t_a = jobs[0].servicer.telemetry()
    t_b = jobs[1].servicer.telemetry()
    assert t_a["job"]["steps_per_sec"] == pytest.approx(5.0)
    assert t_b["job"]["steps_per_sec"] == pytest.approx(11.0)
    assert jobs[0].servicer.worker_record_counts == {7: 32}
    assert jobs[1].servicer.worker_record_counts == {7: 64}


def test_misrouted_progress_report_dropped_by_job_servicer():
    # Defense in depth below the router: a per-job servicer handed a
    # report stamped for a DIFFERENT job refuses it.
    job = make_job(1, "a")
    job.servicer.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=0, record_count=32, job_id=2, steps_per_sec=3.0,
        steps_done=5,
    ))
    assert job.servicer.worker_record_counts == {}
    assert job.servicer.telemetry()["workers"] == {}


def test_rendezvous_epoch_spaces_are_per_job():
    _reg, _ctrl, sv, jobs = make_cluster(
        [dict(name="a", rendezvous=True),
         dict(name="b", rendezvous=True)],
        pool_size=2,
    )
    a_id, b_id = jobs[0].job_id, jobs[1].job_id
    sv.report_train_loop_status(pb.ReportTrainLoopStatusRequest(
        worker_host="worker-0", status=pb.LOOP_START, job_id=a_id))
    sv.report_train_loop_status(pb.ReportTrainLoopStatusRequest(
        worker_host="worker-1", status=pb.LOOP_START, job_id=b_id))
    import time
    time.sleep(0.1)
    ra = sv.get_comm_rank(pb.GetCommRankRequest(
        worker_host="worker-0", job_id=a_id))
    rb = sv.get_comm_rank(pb.GetCommRankRequest(
        worker_host="worker-1", job_id=b_id))
    # each job's world holds only its own worker
    assert (ra.rank_id, ra.world_size) == (0, 1)
    assert (rb.rank_id, rb.world_size) == (0, 1)
    # a worker with no job assignment has no world
    r_none = sv.get_comm_rank(pb.GetCommRankRequest(
        worker_host="worker-9"))
    assert r_none.rank_id == -1


# -- the shrink path ---------------------------------------------------------

def drain_job(job, worker_id=99):
    while True:
        task = job.task_manager.get(worker_id)
        if task is None:
            break
        job.task_manager.report(task.id, True)


def test_drain_requeues_in_flight_task_without_burning_retry():
    registry, controller, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=1), dict(name="b", n_tasks=8)],
        pool_size=2, moves_per_tick=4,
    )
    res = sv.get_task(pb.GetTaskRequest(worker_id=0))   # job a's task
    a = next(j for j in jobs if j.job_id == res.job_id)
    b = next(j for j in jobs if j.job_id != res.job_id)
    task_id = res.task.id
    # controller shrinks job a by force: move its one worker to b
    controller._apply_move(0, a.job_id, b)
    counts = a.task_manager.counts()
    assert counts["todo"] == 1 and counts["doing"] == 0
    # the task went back WITHOUT a retry charged
    pending = next(iter(a.task_manager._todo))
    assert pending.id == task_id and pending.retry_count == 0
    # the worker, mid-task through the move, reports success late:
    # accepted from the queue, completed exactly once
    result = sv.report_task_result(pb.ReportTaskResultRequest(
        task_id=task_id, job_id=a.job_id))
    assert result is not None
    counts = a.task_manager.counts()
    assert counts["completed"][int(pb.TRAINING)] == 1
    assert counts["todo"] == 0


def test_controller_moves_rate_limited_one_per_tick():
    registry, controller, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=2), dict(name="b", n_tasks=12)],
        pool_size=4, moves_per_tick=1,
    )
    held = {}
    for wid in range(4):
        res = sv.get_task(pb.GetTaskRequest(worker_id=wid))
        held[wid] = res
    a, b = jobs
    drain_job(a)   # nothing left in job a
    for wid, res in held.items():
        if res.job_id == a.job_id and res.task.id > 0:
            sv.report_task_result(pb.ReportTaskResultRequest(
                task_id=res.task.id, job_id=a.job_id))
    m1 = controller.tick()
    assert a.state == FINISHED
    assert len(m1) == 1                     # one drained worker per tick
    m2 = controller.tick()
    assert len(m2) == 1
    assert registry.status()["workers_assigned"] == {
        "a": 0, "b": 4,
    }
    # every move is a journal-visible assign decision with prev set
    assert registry.decision_counts["assign"] >= 6   # 4 regs + 2 moves


def test_decision_and_handover_stitch_into_one_trace_component():
    registry, controller, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=1), dict(name="b", n_tasks=8)],
        pool_size=2, moves_per_tick=1,
    )
    tracer = tracing.default_tracer()
    if not tracer.enabled:
        pytest.skip("tracing disabled in this environment")
    res = sv.get_task(pb.GetTaskRequest(worker_id=0))
    a = next(j for j in jobs if j.job_id == res.job_id)
    b = next(j for j in jobs if j.job_id != res.job_id)
    sv.report_task_result(pb.ReportTaskResultRequest(
        task_id=res.task.id, job_id=a.job_id))
    controller.tick()                       # a finished; move decided
    # the worker's next poll runs inside its rpc.server span (the
    # interceptor's role here): the handover event must link back to
    # the decision's trace
    with tracer.span("rpc.server/get_task"):
        res2 = sv.get_task(pb.GetTaskRequest(worker_id=0,
                                             job_id=a.job_id))
    assert res2.job_id == b.job_id and res2.job_config
    components = tracing.trace_components(tracer.recorder.snapshot())
    linked = [
        c for c in components
        if {"sched.resize", "sched.worker_reassigned"} <= {
            e["name"] for e in c
        }
    ]
    assert linked, "resize decision and worker re-register must share " \
                   "one connected trace component"


# -- journaled decisions + replay -------------------------------------------

def test_sched_records_replay_to_exact_assignment_map(tmp_path):
    jdir = str(tmp_path / "sched")
    journal = JournalWriter(jdir)
    registry, controller, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=1), dict(name="b", n_tasks=8)],
        pool_size=2, journal=journal, moves_per_tick=1,
    )
    res = sv.get_task(pb.GetTaskRequest(worker_id=0))
    sv.get_task(pb.GetTaskRequest(worker_id=1))
    a = next(j for j in jobs if j.job_id == res.job_id)
    sv.report_task_result(pb.ReportTaskResultRequest(
        task_id=res.task.id, job_id=a.job_id))
    moves = controller.tick()
    assert len(moves) == 1                  # the mid-resize moment:
    journal.close()                         # crash before further moves
    state = replay_journal(jdir)
    # the replayed schedule is exactly what the dying master committed
    assert state.sched_assignments == {
        w: j for w, j in
        ((0, moves[0][2]), (1, registry.status()["assignments"]["1"]))
    }
    assert state.sched_jobs[a.job_id]["state"] == FINISHED
    assert state.sched_decisions["assign"] == 3   # 2 regs + 1 move
    # a fresh registry (the restarted master) restores the map exactly
    registry2 = JobRegistry(pool_size=0)
    jobs2 = [make_job(1, "a", n_tasks=1), make_job(2, "b", n_tasks=8)]
    for job in jobs2:
        registry2.submit(job, journal=False)
    registry2.restore_from_journal(state)
    assert registry2.status()["assignments"] == (
        registry.status()["assignments"]
    )
    assert [j.state for j in jobs2] == [j.state for j in jobs]


def test_sched_journal_write_ahead_of_drain(tmp_path):
    # commit_move makes the decision durable BEFORE any effect: a
    # journal closed immediately after commit_move already replays the
    # new assignment.
    jdir = str(tmp_path / "sched")
    journal = JournalWriter(jdir)
    registry = JobRegistry(journal=journal, pool_size=2)
    registry.submit(make_job(1, "a"))
    registry.submit(make_job(2, "b"))
    registry.ensure_assigned(0)
    prev = registry.commit_move(0, 2, link="feedbeef")
    state = replay_journal(jdir)            # no close/flush needed:
    assert state.sched_assignments == {0: 2}   # commit_move fsync'd
    assert prev == 1
    assert registry.pop_link(0) == "feedbeef"
    assert registry.pop_link(0) is None     # one-shot
    journal.close()


def test_stale_worker_evicted_and_tasks_requeued():
    registry, controller, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=4)], pool_size=2,
        worker_stale_secs=0.0,
    )
    res = sv.get_task(pb.GetTaskRequest(worker_id=0))
    assert res.task.id > 0
    import time
    time.sleep(0.01)
    controller.tick()
    counts = jobs[0].task_manager.counts()
    assert counts["doing"] == 0             # requeued, no retry burned
    assert registry.status()["assignments"] == {}


# -- observability surface ---------------------------------------------------

def test_status_and_metrics_surface():
    registry, _ctrl, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=2, min_workers=2),
         dict(name="b", n_tasks=2, min_workers=4)],   # queued
        pool_size=4,
    )
    sv.get_task(pb.GetTaskRequest(worker_id=0))
    sv.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=0, record_count=32, job_id=jobs[0].job_id,
        steps_per_sec=2.5, steps_done=4,
    ))
    status = collect_multitenant_status(registry)
    assert status["sched"]["pending_jobs"] == 1
    assert status["jobs"]["a"]["state"] == RUNNING
    assert status["jobs"]["b"]["state"] == PENDING
    assert status["jobs"]["a"]["telemetry"]["job"][
        "steps_per_sec"] == pytest.approx(2.5)
    text = multitenant_to_prometheus(status)
    assert 'elasticdl_sched_workers_assigned{job="a"} 1' in text
    assert 'elasticdl_sched_workers_assigned{job="b"} 0' in text
    assert "elasticdl_sched_pending_jobs 1" in text
    assert 'elasticdl_sched_decisions_total{op="assign"} 1' in text
    assert 'elasticdl_job_steps_per_sec{job="a"} 2.5' in text
    assert 'elasticdl_tasks_todo{job="a"}' in text


def test_handshake_survives_target_job_finishing_before_poll():
    """A move whose target job drains before the moved worker's first
    post-move poll must still deliver the config and pop the decision
    link — the worker would otherwise adopt the new job id with the
    old pipeline, and the decision trace would never stitch."""
    registry2, controller2, sv2, jobs2 = make_cluster(
        [dict(name="a", n_tasks=1), dict(name="b", n_tasks=1),
         dict(name="c", n_tasks=8, max_workers=1)],
        pool_size=3, moves_per_tick=4,
    )
    held = {w: sv2.get_task(pb.GetTaskRequest(worker_id=w))
            for w in range(3)}
    a2 = next(j for j in jobs2 if j.spec.name == "a")
    b2 = next(j for j in jobs2 if j.spec.name == "b")
    wid = next(w for w, r in held.items() if r.job_id == a2.job_id)
    wid_b = next(w for w, r in held.items() if r.job_id == b2.job_id)
    # both small jobs drain: their holders report their single tasks
    sv2.report_task_result(pb.ReportTaskResultRequest(
        task_id=held[wid].task.id, job_id=a2.job_id))
    sv2.report_task_result(pb.ReportTaskResultRequest(
        task_id=held[wid_b].task.id, job_id=b2.job_id))
    # the move lands just before b is swept finished
    controller2._apply_move(wid, a2.job_id, b2)
    controller2.tick()   # a and b finished; c at max: nobody moves
    assert b2.state == FINISHED
    res3 = sv2.get_task(pb.GetTaskRequest(worker_id=wid,
                                          job_id=a2.job_id))
    assert res3.task.type == pb.WAIT        # parked, c still running
    assert res3.job_id == b2.job_id
    assert res3.job_config                  # handshake delivered
    assert registry2.pop_link(wid) is None  # link consumed, not leaked


def test_progress_reports_count_as_liveness():
    registry, controller, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=4)], pool_size=1,
        worker_stale_secs=0.05,
    )
    res = sv.get_task(pb.GetTaskRequest(worker_id=0))
    assert res.task.id > 0
    import time
    # mid-task: no get_task for longer than the stale window, but
    # progress reports keep flowing — the sweep must NOT evict
    for _ in range(3):
        time.sleep(0.03)
        sv.report_batch_done(pb.ReportBatchDoneRequest(
            worker_id=0, record_count=32, job_id=jobs[0].job_id))
        controller.tick()
    assert registry.status()["assignments"] == {
        "0": jobs[0].job_id,
    }
    # a released worker's straggler report does not re-open the pool
    registry.release_worker(0, reason="exit")
    sv.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=0, record_count=32, job_id=jobs[0].job_id))
    assert registry.known_worker_count() == 0


def test_cross_job_move_rebuilds_even_with_identical_config():
    """Tenant isolation: the first assignment may reuse the eagerly
    built pool-template pipeline, but a CROSS-JOB move must rebuild
    even when the configs are pipeline-identical — the old trainer
    holds the previous tenant's trained parameters."""
    from types import SimpleNamespace

    from elasticdl_tpu.worker.worker import Worker

    cfg = {"job": "a", "job_id": 1, "model_zoo": "mnist",
           "model_params": "", "data_origin": "synthetic_mnist:128",
           "batch_size": 32, "num_minibatches_per_task": 4, "seed": 0,
           "checkpoint_dir": "", "distribution_strategy": "local"}
    builds = []

    def factory(c):
        builds.append(c["job_id"])
        return (SimpleNamespace(),
                SimpleNamespace(feed=None, callbacks=[]),
                SimpleNamespace())

    mc = SimpleNamespace(job_id=0, job_config=None, worker_id=0)
    spec = SimpleNamespace(feed=None, callbacks=[])
    worker = Worker(
        mc, SimpleNamespace(), spec, None, batch_size=32,
        job_context_factory=factory, initial_job_config=dict(cfg),
    )
    mc.job_id = 1
    mc.job_config = dict(cfg)
    worker._maybe_switch_job()
    assert builds == []                     # template matches: fast path
    mc.job_id = 2
    mc.job_config = dict(cfg, job="b", job_id=2)
    worker._maybe_switch_job()
    assert builds == [2]                    # identical config, new job:
    #                                         rebuilt for isolation


def test_unassigned_worker_released_on_exit_task():
    """Pool larger than total demand: workers parked UNASSIGNED must
    still leave the known set when they collect their exit task, or
    the unmanaged-pool drain gate would hold the run loop for the
    full grace window."""
    registry, controller, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=1, max_workers=1)], pool_size=3,
    )
    res0 = sv.get_task(pb.GetTaskRequest(worker_id=0))
    assert res0.task.id > 0
    # workers 1..2 park unassigned (job at max): known but jobless
    for wid in (1, 2):
        res = sv.get_task(pb.GetTaskRequest(worker_id=wid))
        assert res.task.type == pb.WAIT
    assert registry.known_worker_count() == 3
    sv.report_task_result(pb.ReportTaskResultRequest(
        task_id=res0.task.id, job_id=jobs[0].job_id))
    controller.tick()
    assert registry.all_finished()
    for wid in range(3):
        res = sv.get_task(pb.GetTaskRequest(worker_id=wid))
        assert res.task.id == -1 and res.task.type != pb.WAIT
    assert registry.known_worker_count() == 0   # drain gate closes


def test_impossible_min_workers_fails_fast(tmp_path):
    import json as _json

    from elasticdl_tpu.master.main import build_multitenant_master
    from elasticdl_tpu.utils.args import parse_master_args

    spec_path = str(tmp_path / "jobs.json")
    with open(spec_path, "w") as fh:
        _json.dump([{"name": "a", "min_workers": 8,
                     "data_origin": "synthetic_mnist:128"}], fh)
    args = parse_master_args([
        "--jobs_spec", spec_path, "--num_workers", "4",
    ])
    with pytest.raises(ValueError, match="could never be admitted"):
        build_multitenant_master(args)


def test_multitenant_metrics_include_per_worker_gauges():
    """The multi-tenant renderer shares the per-job gauge helpers with
    the single-job one: per-worker health series must appear under a
    job label, not silently vanish under --jobs_spec."""
    registry, _ctrl, sv, jobs = make_cluster(
        [dict(name="a", n_tasks=2)], pool_size=1,
    )
    sv.get_task(pb.GetTaskRequest(worker_id=3))
    sv.report_batch_done(pb.ReportBatchDoneRequest(
        worker_id=3, record_count=32, job_id=jobs[0].job_id,
        steps_per_sec=4.0, sync_fraction=0.25, steps_done=9,
    ))
    text = multitenant_to_prometheus(
        collect_multitenant_status(registry)
    )
    assert ('elasticdl_worker_steps_per_sec{job="a",worker="3"} 4.0'
            in text)
    assert ('elasticdl_worker_sync_fraction{job="a",worker="3"} 0.25'
            in text)
    assert 'elasticdl_worker_steps_done{job="a",worker="3"} 9' in text


def test_jobs_spec_validation():
    with pytest.raises(ValueError):
        JobSpec("x", min_workers=2, max_workers=1)
    with pytest.raises(ValueError):
        JobSpec("x", weight=0)
    with pytest.raises(ValueError):
        JobSpec("x", distribution_strategy="ps")
    with pytest.raises(ValueError):
        JobSpec.from_dict({"name": "x", "bogus_knob": 1})
    spec = JobSpec.from_dict(
        {"name": "x", "min_workers": 0},
        defaults=type("A", (), {"model_zoo": "mnist",
                                "data_origin": "synthetic_mnist:64"})(),
    )
    assert spec.data_origin == "synthetic_mnist:64"
    assert spec.min_workers == 0
