"""ZeRO-1 weight-update sharding (worker/zero.py + --zero1).

The contract under test, end to end:

 - full coverage: EVERY non-scalar optimizer leaf shards (flat padded
   dim 0 over the data axis), including the odd shapes the old stub
   silently replicated;
 - trajectory: zero1 on vs off is BIT-identical, per-step and through
   fused windows, with and without gradient accumulation;
 - elastic: a world re-form re-partitions live shards device-to-device
   with Adam moments preserved bit-exactly, and a same-size re-form
   continues the trajectory bitwise;
 - persistence: checkpoints hold the original-shape unpadding view and
   round-trip sharded -> file -> sharded, and across modes;
 - off switch: ``--zero1 false`` is the exact old replicated layout.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from elasticdl_tpu.models import mnist
from elasticdl_tpu.utils.checkpoint import CheckpointSaver
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer
from elasticdl_tpu.worker.zero import ZeroPartitioner


@pytest.fixture(scope="module")
def spec():
    return mnist.model_spec(learning_rate=1e-3)


def make_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), axis_names=("data",))


def host_state(trainer):
    """Original-shape host view of the trainer's optimizer state."""
    return trainer._opt_state_on_host()


def assert_trees_bitwise(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- trajectory equivalence ------------------------------------------------


def test_zero1_per_step_bitwise_equivalence(spec):
    """Same seed, same batches: zero1 losses == replicated losses,
    float-exact, over enough steps for 1-ulp drift to show if the
    update were not numerically pinned."""
    xs, ys = mnist.synthetic_data(n=64, seed=21)
    mesh = make_mesh(8)
    base = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=7)
    z1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=7,
                           zero1=True)
    for _ in range(12):
        loss_b, _ = base.train_minibatch(xs, ys)
        loss_z, _ = z1.train_minibatch(xs, ys)
        assert float(loss_b) == float(loss_z)


@pytest.mark.parametrize("window", [1, 4])
def test_zero1_fused_window_bitwise_equivalence(spec, window):
    """K fused steps per dispatch: the zero1 window (opt-state carry =
    1/N flat shards) reproduces the replicated window bit-for-bit."""
    xs, ys = mnist.synthetic_data(n=64, seed=23)
    mesh = make_mesh(8)
    base = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=9)
    z1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=9,
                           zero1=True)
    for _ in range(2):
        pb = [base.prepare_batch(xs, ys) for _ in range(window)]
        pz = [z1.prepare_batch(xs, ys) for _ in range(window)]
        lb, _ = base.train_window(base.stage_window(pb))
        lz, _ = z1.train_window(z1.stage_window(pz))
        np.testing.assert_array_equal(np.asarray(lb), np.asarray(lz))


def test_zero1_accum_bitwise_equivalence(spec):
    """Gradient accumulation (the fixed-global-batch elastic resize
    math) composes with the sharded update bit-exactly."""
    xs, ys = mnist.synthetic_data(n=64, seed=25)
    mesh = make_mesh(4)
    base = CollectiveTrainer(spec, batch_size=8, mesh=mesh,
                             accum_steps=2, rng_seed=11)
    z1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh,
                           accum_steps=2, rng_seed=11, zero1=True)
    for _ in range(6):
        loss_b, _ = base.train_minibatch(xs, ys)
        loss_z, _ = z1.train_minibatch(xs, ys)
        assert float(loss_b) == float(loss_z)


# -- full coverage + unpad fidelity ----------------------------------------


def test_zero1_full_coverage_every_nonscalar_leaf_sharded(spec):
    """The old stub replicated any leaf whose dim 0 didn't divide the
    shard count (e.g. the [10] output bias).  The flat padded layout
    shards them ALL; only rank-0 scalars (Adam's step count) remain
    replicated."""
    mesh = make_mesh(8)
    z1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, zero1=True)
    xs, ys = mnist.synthetic_data(n=64, seed=27)
    z1.train_minibatch(xs, ys)
    replicated_nonscalar = [
        np.shape(leaf)
        for leaf in jax.tree_util.tree_leaves(z1._opt_state)
        if np.ndim(leaf) >= 1 and leaf.sharding.spec != P("data")
    ]
    assert replicated_nonscalar == []
    report = z1.zero1_report()
    assert report["mode"] == "zero1"
    # moments ~2x params >> padding + the scalar count: the measured
    # per-device bytes must sit within 1% of replicated/N
    assert report["per_device_bytes"] <= (
        report["replicated_equiv_bytes"] / report["num_shards"] * 1.01
    )


def test_unpad_fidelity_odd_shapes():
    """Flat-pad then unpad is the identity for shapes that do NOT
    divide the shard count (the [10] bias pads to [16]), bit-exact,
    with padding zeros never leaking."""
    mesh = make_mesh(8)
    import optax

    tx = optax.adam(1e-3)
    rng = np.random.RandomState(0)
    params = {
        "odd_bias": rng.randn(10).astype(np.float32),
        "odd_mat": rng.randn(7, 3).astype(np.float32),
        "even": rng.randn(16).astype(np.float32),
    }
    part = ZeroPartitioner(tx, params, mesh)
    flat = part.flatten_params(params)
    assert np.shape(flat["odd_bias"]) == (16,)
    assert np.shape(flat["odd_mat"]) == (24,)
    assert np.asarray(flat["odd_bias"])[10:].tolist() == [0.0] * 6
    back = part.unflatten_params(flat)
    assert_trees_bitwise(params, back)
    # state round-trip through the same specs (moments mirror params)
    state = tx.init(params)
    back_state = part.unflatten_state(part.flatten_state(state))
    assert_trees_bitwise(state, back_state)


# -- elastic re-partition --------------------------------------------------


def test_repartition_preserves_moments_bitwise(spec):
    """World resize 8 -> 4 -> 8 with live shards: the unpadded moment
    view is bit-identical across every re-partition, and the moves are
    device-to-device (no host bounce counter)."""
    xs, ys = mnist.synthetic_data(n=64, seed=29)
    z1 = CollectiveTrainer(spec, batch_size=8, mesh=make_mesh(8),
                           zero1=True, rng_seed=13)
    for _ in range(3):
        z1.train_minibatch(xs, ys)
    before = host_state(z1)
    z1.rebuild(make_mesh(4))  # half the world died
    assert_trees_bitwise(before, host_state(z1))
    counters = z1.timing.counters()
    assert counters.get("zero1_repartitions") == 1
    assert counters.get("zero1_reshard_bytes", 0) > 0
    assert counters.get("reshard_host_fallbacks", 0) == 0
    loss, _ = z1.train_minibatch(xs[:32], ys[:32])
    assert np.isfinite(float(loss))
    mid = host_state(z1)
    z1.rebuild(make_mesh(8))  # the replacements arrived
    assert_trees_bitwise(mid, host_state(z1))
    assert z1.timing.counters().get("zero1_repartitions") == 2


def test_same_size_reform_trajectory_bitwise(spec):
    """The common churn case — a peer is replaced, world SIZE is
    unchanged: the re-formed trainer continues the no-churn loss
    trajectory bit-for-bit (the VirtualFlow-style exactness the churn
    drills verify)."""
    xs, ys = mnist.synthetic_data(n=64, seed=31)
    ref = CollectiveTrainer(spec, batch_size=8, mesh=make_mesh(8),
                            zero1=True, rng_seed=15)
    churn = CollectiveTrainer(spec, batch_size=8, mesh=make_mesh(8),
                              zero1=True, rng_seed=15)
    ref_losses = [float(ref.train_minibatch(xs, ys)[0])
                  for _ in range(6)]
    churn_losses = [float(churn.train_minibatch(xs, ys)[0])
                    for _ in range(3)]
    churn.rebuild(make_mesh(8))  # epoch re-form, same world size
    churn_losses += [float(churn.train_minibatch(xs, ys)[0])
                     for _ in range(3)]
    assert churn_losses == ref_losses


def test_snapshot_to_host_gathers_sharded_state(spec):
    """snapshot_to_host on a zero1 world gathers the flat shards into
    original-shape host numpy (the multi-controller-safe path), and a
    rebuild from that snapshot resumes the exact trajectory."""
    xs, ys = mnist.synthetic_data(n=64, seed=33)
    ref = CollectiveTrainer(spec, batch_size=8, mesh=make_mesh(8),
                            zero1=True, rng_seed=17)
    t = CollectiveTrainer(spec, batch_size=8, mesh=make_mesh(8),
                          zero1=True, rng_seed=17)
    ref_losses = [float(ref.train_minibatch(xs, ys)[0])
                  for _ in range(4)]
    [t.train_minibatch(xs, ys) for _ in range(2)]
    t.snapshot_to_host()
    state = t._opt_state
    leaves, _ = jax.tree_util.tree_flatten(state)
    assert all(isinstance(leaf, np.ndarray) for leaf in leaves)
    # original (unpadded) shapes on host — not the flat wire form
    shapes = {np.shape(leaf) for leaf in leaves if np.ndim(leaf) >= 1}
    assert (3136, 128) in {s for s in shapes}
    t.rebuild(make_mesh(8))
    resumed = [float(t.train_minibatch(xs, ys)[0]) for _ in range(2)]
    assert resumed == ref_losses[2:]


# -- persistence -----------------------------------------------------------


def test_zero1_checkpoint_roundtrip_sharded(spec, tmp_path):
    """sharded -> checkpoint -> restore -> sharded: the file holds
    original shapes, the restored trainer resumes the exact
    trajectory, and its state is sharded again after rebuild."""
    saver = CheckpointSaver(str(tmp_path))
    xs, ys = mnist.synthetic_data(n=64, seed=35)
    mesh = make_mesh(8)
    ref = CollectiveTrainer(spec, batch_size=8, mesh=mesh,
                            zero1=True, rng_seed=19)
    ref_losses = [float(ref.train_minibatch(xs, ys)[0])
                  for _ in range(4)]
    t1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, zero1=True,
                           rng_seed=19, checkpoint_saver=saver,
                           checkpoint_steps=2)
    t1.train_minibatch(xs, ys)
    t1.train_minibatch(xs, ys)
    t1.flush_checkpoints()
    dense, _, _ = saver.load()
    # checkpoint holds the UNPADDED original shapes (mode-portable)
    assert dense["opt/0/mu/Dense_0/kernel"].shape == (3136, 128)
    assert dense["opt/0/mu/Dense_1/bias"].shape == (10,)
    t2 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, zero1=True,
                           rng_seed=99, checkpoint_saver=saver)
    assert t2.init_from_checkpoint() and t2.version == 2
    sharded = [
        leaf for leaf in jax.tree_util.tree_leaves(t2._opt_state)
        if np.ndim(leaf) >= 1 and leaf.sharding.spec == P("data")
    ]
    assert sharded
    resumed = [float(t2.train_minibatch(xs, ys)[0]) for _ in range(2)]
    assert resumed == ref_losses[2:]


def test_zero1_checkpoint_portable_to_replicated(spec, tmp_path):
    """A checkpoint written by a zero1 trainer restores into a
    replicated trainer (and the trajectory matches bitwise) — the
    on-disk format is mode-independent."""
    saver = CheckpointSaver(str(tmp_path))
    xs, ys = mnist.synthetic_data(n=64, seed=37)
    mesh = make_mesh(8)
    ref = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=20)
    ref_losses = [float(ref.train_minibatch(xs, ys)[0])
                  for _ in range(4)]
    t1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, zero1=True,
                           rng_seed=20, checkpoint_saver=saver,
                           checkpoint_steps=2)
    t1.train_minibatch(xs, ys)
    t1.train_minibatch(xs, ys)
    t1.flush_checkpoints()
    t2 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, rng_seed=99,
                           checkpoint_saver=saver)
    assert t2.init_from_checkpoint()
    resumed = [float(t2.train_minibatch(xs, ys)[0]) for _ in range(2)]
    assert resumed == ref_losses[2:]


# -- off switch + observability --------------------------------------------


def test_zero1_off_is_exact_old_layout(spec):
    """--zero1 false keeps the replicated layout: original leaf
    shapes, every leaf replicated, no partitioner, no zero1 counters."""
    mesh = make_mesh(8)
    t = CollectiveTrainer(spec, batch_size=8, mesh=mesh)
    xs, ys = mnist.synthetic_data(n=64, seed=39)
    t.train_minibatch(xs, ys)
    assert t._zero is None and not t._opt_is_flat
    for leaf in jax.tree_util.tree_leaves(t._opt_state):
        if np.ndim(leaf) >= 1:
            assert leaf.sharding.spec == P()
    shapes = {np.shape(leaf)
              for leaf in jax.tree_util.tree_leaves(t._opt_state)}
    assert (3136, 128) in shapes  # not flattened
    assert t.zero1_report()["mode"] == "replicated"
    counters = t.timing.counters()
    assert not any(k.startswith("zero1_") for k in counters)
    assert "zero1" not in t.timing.summary()


def test_zero1_timing_section_and_report(spec):
    """Dispatch counts reduce-scatter/all-gather payload bytes; the
    counters surface as the ``zero1`` section of Timing.summary() and
    report() handles the mixed summary without crashing."""
    mesh = make_mesh(8)
    z1 = CollectiveTrainer(spec, batch_size=8, mesh=mesh, zero1=True)
    xs, ys = mnist.synthetic_data(n=64, seed=41)
    z1.train_minibatch(xs, ys)
    prepared = [z1.prepare_batch(xs, ys) for _ in range(3)]
    z1.train_window(z1.stage_window(prepared))
    section = z1.timing.summary()["zero1"]
    flat_bytes = z1._zero.flat_param_bytes()
    assert section["zero1_reduce_scatter_bytes"] == flat_bytes * 4
    assert section["zero1_all_gather_bytes"] == flat_bytes * 4
    z1.timing.report()  # must tolerate the counter section


def test_zero1_single_device_mesh(spec):
    """A 1-device mesh world degenerates gracefully: zero1 stays
    active (1 shard == replicated) and steps run."""
    z1 = CollectiveTrainer(spec, batch_size=16, mesh=make_mesh(1),
                           zero1=True)
    xs, ys = mnist.synthetic_data(n=16, seed=43)
    loss, _ = z1.train_minibatch(xs, ys)
    assert np.isfinite(float(loss))
    assert z1.zero1_report()["num_shards"] == 1
