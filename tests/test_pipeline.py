"""Microbatch pipeline parallelism: parity with sequential execution.

The pipeline must be semantically invisible — same outputs, same loss,
same gradients as running the full layer stack sequentially on one
device (VERDICT r1 #4's acceptance bar).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel.mesh import build_mesh
from elasticdl_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
)

L, E = 8, 16  # stacked layers, width


def make_params(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": jnp.asarray(rng.randn(L, E, E).astype(np.float32) * 0.3),
        "b": jnp.asarray(rng.randn(L, E).astype(np.float32) * 0.1),
    }


def layer(x, w, b):
    return jnp.tanh(x @ w + b)


def sequential_apply(params, x):
    def body(x, wb):
        w, b = wb
        return layer(x, w, b), None

    y, _ = jax.lax.scan(body, x, (params["w"], params["b"]))
    return y


def stage_fn(stage_params, x):
    # Each stage scans its own L/S slice of the stack.
    def body(x, wb):
        w, b = wb
        return layer(x, w, b), None

    y, _ = jax.lax.scan(body, x, (stage_params["w"], stage_params["b"]))
    return y


def run_pipeline(mesh, params, x, num_microbatches, remat=False):
    xm = split_microbatches(x, num_microbatches)
    ym = pipeline_apply(
        stage_fn, params, xm, mesh=mesh,
        num_microbatches=num_microbatches, remat=remat,
    )
    return merge_microbatches(ym)


@pytest.mark.parametrize("pp,mb", [(2, 4), (4, 8), (8, 8)])
def test_forward_parity(pp, mb):
    mesh = build_mesh(pp=pp)
    params = make_params()
    x = jnp.asarray(
        np.random.RandomState(1).randn(16, E).astype(np.float32)
    )
    want = sequential_apply(params, x)
    got = run_pipeline(mesh, params, x, mb)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_single_microbatch_degenerate():
    mesh = build_mesh(pp=2)
    params = make_params()
    x = jnp.ones((4, E), jnp.float32)
    got = run_pipeline(mesh, params, x, 1)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(sequential_apply(params, x)),
        rtol=1e-5,
    )


@pytest.mark.parametrize("remat", [False, True])
def test_gradient_parity(remat):
    """Backward through the pipeline (autodiff of scan+ppermute) matches
    the sequential gradients — the 1F1B-equivalent drain schedule falls
    out of the transpose."""
    mesh = build_mesh(pp=4)
    params = make_params()
    x = jnp.asarray(
        np.random.RandomState(2).randn(16, E).astype(np.float32)
    )
    tgt = jnp.asarray(
        np.random.RandomState(3).randn(16, E).astype(np.float32)
    )

    def loss_seq(p):
        return jnp.mean((sequential_apply(p, x) - tgt) ** 2)

    def loss_pipe(p):
        return jnp.mean((run_pipeline(mesh, p, x, 8, remat=remat) - tgt) ** 2)

    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    for k in g_seq:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]),
            rtol=1e-4, atol=1e-5,
        )


def test_pipeline_with_dp_axis():
    """pp composes with dp: batch sharded over dp (auto axis), layers
    pipelined over pp."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_mesh(dp=2, pp=4)
    params = make_params()
    x = jnp.asarray(
        np.random.RandomState(4).randn(16, E).astype(np.float32)
    )
    want = sequential_apply(params, x)
    xm = split_microbatches(x, 4)
    xm = jax.device_put(
        xm, NamedSharding(mesh, P(None, "dp"))
    )

    @jax.jit
    def f(params, xm):
        # x_spec only names manual axes (pp); the dp batch sharding rides
        # along as an auto axis via GSPMD.
        return pipeline_apply(
            stage_fn, params, xm, mesh=mesh, num_microbatches=4,
        )

    got = merge_microbatches(f(params, xm))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("variant", ["dense", "gqa+window"])
def test_transformer_pipelined_matches_sequential(variant):
    """End-to-end: the flagship transformer's pipelined forward (pp=2,
    dp=2) reproduces the plain scanned forward's loss and gradients —
    incl. the GQA + sliding-window attention variants riding through
    the pipeline unchanged."""
    from elasticdl_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=128, dim=32, num_heads=4, num_layers=4,
        max_seq_len=16, dtype="float32",
        **({"num_kv_heads": 2, "window": 8}
           if variant == "gqa+window" else {}),
    )
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(5).randint(0, 128, size=(8, 16)),
        jnp.int32,
    )
    mesh = build_mesh(dp=2, pp=4)

    def loss_seq(p):
        return tfm.next_token_loss(
            tfm.forward(p, tokens, cfg, mesh=None), tokens
        ).mean()

    def loss_pipe(p):
        return tfm.next_token_loss(
            tfm.forward_pipelined(p, tokens, cfg, mesh, 4), tokens
        ).mean()

    l_seq, g_seq = jax.value_and_grad(loss_seq)(params)
    l_pipe, g_pipe = jax.jit(jax.value_and_grad(loss_pipe))(params)
    np.testing.assert_allclose(float(l_pipe), float(l_seq), rtol=1e-5)
    flat_seq = jax.tree_util.tree_leaves(g_seq)
    flat_pipe = jax.tree_util.tree_leaves(g_pipe)
    for a, b in zip(flat_pipe, flat_seq):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_transformer_pipelined_rejects_sp():
    from elasticdl_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab_size=64, dim=16, num_heads=2,
                                num_layers=2, dtype="float32")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    mesh = build_mesh(pp=2, sp=2, dp=2)
    tokens = jnp.zeros((4, 8), jnp.int32)
    with pytest.raises(ValueError, match="sp=1"):
        tfm.forward_pipelined(params, tokens, cfg, mesh, 2)


def test_pipelined_moe_aux_matches_sequential():
    """The pipelined path recovers the EXACT full-batch MoE aux by
    accumulating linear router statistics (bubble ticks masked) —
    identical objective to the scanned forward, at any M."""
    from elasticdl_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=128, dim=32, num_heads=4, num_layers=4,
        max_seq_len=16, dtype="float32", moe_experts=4, moe_top_k=2,
    )
    params = tfm.init_params(jax.random.PRNGKey(7), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(7).randint(0, 128, size=(8, 16)),
        jnp.int32,
    )
    mesh = build_mesh(dp=2, pp=4)
    logits_seq, aux_seq = tfm.forward(params, tokens, cfg,
                                      return_aux=True)
    logits_pipe, aux_pipe = jax.jit(
        lambda p, t: tfm.forward_pipelined(
            p, t, cfg, mesh, 4, return_aux=True
        )
    )(params, tokens)
    np.testing.assert_allclose(np.asarray(logits_pipe),
                               np.asarray(logits_seq),
                               rtol=5e-4, atol=1e-5)
    # The pipeline accumulates the LINEAR router statistics, so its
    # aux equals the full-batch Switch value EXACTLY — same objective
    # regardless of the microbatch count.
    np.testing.assert_allclose(float(aux_pipe), float(aux_seq),
                               rtol=1e-4)
    aux_pipe_m2 = jax.jit(
        lambda p, t: tfm.forward_pipelined(
            p, t, cfg, mesh, 2, return_aux=True
        )
    )(params, tokens)[1]
    np.testing.assert_allclose(float(aux_pipe_m2), float(aux_seq),
                               rtol=1e-4)


def test_pipelined_moe_grad_parity_through_aux():
    """Backward through the tree-aux accumulation + finalize: gradients
    of (task loss + aux) on the pipelined path match the sequential
    forward's — including the router, which only the aux reaches."""
    from elasticdl_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(
        vocab_size=64, dim=32, num_heads=4, num_layers=4,
        max_seq_len=8, dtype="float32", moe_experts=4, moe_top_k=2,
    )
    params = tfm.init_params(jax.random.PRNGKey(9), cfg)
    tokens = jnp.asarray(
        np.random.RandomState(9).randint(0, 64, size=(8, 8)), jnp.int32
    )
    mesh = build_mesh(dp=2, pp=4)

    def loss_seq(p):
        logits, aux = tfm.forward(p, tokens, cfg, return_aux=True)
        return tfm.next_token_loss(logits, tokens).mean() + 0.01 * aux

    def loss_pipe(p):
        logits, aux = tfm.forward_pipelined(
            p, tokens, cfg, mesh, 4, return_aux=True
        )
        return tfm.next_token_loss(logits, tokens).mean() + 0.01 * aux

    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    router_grad = np.asarray(g_pipe["layers"]["w_router"])
    assert np.abs(router_grad).max() > 0, "router got no gradient"
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-6)
