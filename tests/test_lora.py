"""LoRA fine-tuning of the flagship LM (models/lora.py).

Contracts: zero-delta init reproduces the base model exactly; training
moves ONLY the adapters (frozen base is bitwise unchanged); merging
folds the adaptation into vanilla transformer params that forward /
generate / export consume with no LoRA code; the pretrain -> export ->
adapt-from-export story round-trips.
"""

import dataclasses

import jax
import numpy as np
import pytest

from elasticdl_tpu.models import lora, transformer as tfm
from elasticdl_tpu.utils.pytree import flatten_with_names, to_numpy
from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

LM_KW = dict(vocab_size=128, dim=32, num_heads=4, num_layers=2,
             seq_len=16, dtype="float32")


def make_tokens(b, t, seed):
    return np.random.RandomState(seed).randint(
        0, 128, size=(b, t)).astype(np.int32)


def test_zero_delta_init_matches_base():
    spec = lora.model_spec(rank=4, **LM_KW)
    params = spec.init_fn(jax.random.PRNGKey(0))
    toks = make_tokens(2, 8, seed=1)
    got = np.asarray(spec.apply_fn(params, toks, False))
    want = np.asarray(
        tfm.forward(params["base"], toks, spec.config))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_training_moves_only_adapters():
    spec = lora.model_spec(rank=4, **LM_KW)
    trainer = CollectiveTrainer(spec, batch_size=4)
    before = to_numpy(trainer._params)
    toks = make_tokens(4, 16, seed=2)
    losses = [trainer.train_minibatch(toks, toks)[0] for _ in range(8)]
    after = to_numpy(trainer._params)

    base_b, _ = flatten_with_names(before["base"])
    base_a, _ = flatten_with_names(after["base"])
    for name in base_b:
        np.testing.assert_array_equal(
            base_b[name], base_a[name],
            err_msg="frozen base param %s moved" % name)

    moved = [
        t for t, ab in after["lora"].items()
        if np.abs(ab["B"]).max() > 0
    ]
    assert sorted(moved) == sorted(lora.DEFAULT_TARGETS), moved
    assert losses[-1] < losses[0], losses  # it actually learns


def test_merged_params_fold_exactly():
    spec = lora.model_spec(rank=4, alpha=8, **LM_KW)
    trainer = CollectiveTrainer(spec, batch_size=4)
    toks = make_tokens(4, 16, seed=3)
    for _ in range(3):
        trainer.train_minibatch(toks, toks)
    params = to_numpy(trainer._params)
    merged = lora.merged_params(params, scaling=spec.lora["scaling"])
    probe = make_tokens(2, 8, seed=4)
    want = np.asarray(spec.apply_fn(params, probe, False))
    got = np.asarray(tfm.forward(merged, probe, spec.config))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # merged params drive the vanilla KV-cache decode path
    out = np.asarray(tfm.generate(merged, spec.config, probe,
                                  max_new_tokens=3))
    assert out.shape == (2, 11)


def test_adapt_from_base_export(tmp_path):
    """Pretrain -> export -> LoRA spec loads the exported base."""
    base_spec = tfm.model_spec(**LM_KW)
    trainer = CollectiveTrainer(base_spec, batch_size=4)
    toks = make_tokens(4, 16, seed=5)
    trainer.train_minibatch(toks, toks)

    from elasticdl_tpu.models.callbacks import ModelExporter

    export_dir = str(tmp_path / "base")
    ModelExporter(export_dir, model_name="lm").on_train_end(trainer)

    spec = lora.model_spec(rank=4, base_export=export_dir, **LM_KW)
    params = spec.init_fn(jax.random.PRNGKey(7))
    want, _ = flatten_with_names(to_numpy(trainer._params))
    got, _ = flatten_with_names(to_numpy(params["base"]))
    for name in want:
        np.testing.assert_allclose(
            got[name], want[name], rtol=1e-6, atol=1e-6,
            err_msg="base weight %s not loaded from export" % name)


def test_mlp_targets_and_gqa_window_variant():
    """Adapters on MLP matrices too, under a GQA + sliding-window
    config — merge-at-forward must compose with every variant."""
    spec = lora.model_spec(
        rank=2, lora_targets="wq,wo,w_gate,w_up,w_down",
        num_kv_heads=2, window=4, **LM_KW)
    params = spec.init_fn(jax.random.PRNGKey(0))
    assert sorted(params["lora"]) == [
        "w_down", "w_gate", "w_up", "wo", "wq"]
    toks = make_tokens(2, 16, seed=6)
    out = np.asarray(spec.apply_fn(params, toks, False))
    assert out.shape == (2, 16, 128)
    want = np.asarray(tfm.forward(params["base"], toks, spec.config))
    np.testing.assert_allclose(out, want, rtol=1e-6, atol=1e-6)


def test_train_norms_variant_moves_norms_without_decay():
    """train_norms=True: norm scales train (no weight decay — decay
    would pull the 1.0-initialized RMSNorm scales toward zero), the
    rest of the base stays frozen."""
    spec = lora.model_spec(rank=2, train_norms=True, **LM_KW)
    trainer = CollectiveTrainer(spec, batch_size=4)
    before = to_numpy(trainer._params)
    toks = make_tokens(4, 16, seed=8)
    for _ in range(4):
        trainer.train_minibatch(toks, toks)
    after = to_numpy(trainer._params)
    assert not np.array_equal(before["base"]["ln_f"],
                              after["base"]["ln_f"])
    assert not np.array_equal(before["base"]["layers"]["ln1"],
                              after["base"]["layers"]["ln1"])
    np.testing.assert_array_equal(before["base"]["embed"],
                                  after["base"]["embed"])
    np.testing.assert_array_equal(before["base"]["layers"]["wq"],
                                  after["base"]["layers"]["wq"])


def test_adapt_from_quantized_base_export(tmp_path):
    """An int8-quantized base export works as base_export: load_export
    dequantizes transparently (advisor round-5 catch)."""
    base_spec = tfm.model_spec(**LM_KW)
    trainer = CollectiveTrainer(base_spec, batch_size=4)
    trainer.train_minibatch(make_tokens(4, 16, seed=9),
                            make_tokens(4, 16, seed=9))
    from elasticdl_tpu.models.callbacks import ModelExporter

    export_dir = str(tmp_path / "q8base")
    ModelExporter(export_dir, model_name="lm",
                  quantize="int8").on_train_end(trainer)
    spec = lora.model_spec(rank=2, base_export=export_dir, **LM_KW)
    params = spec.init_fn(jax.random.PRNGKey(3))
    want, _ = flatten_with_names(to_numpy(trainer._params))
    got, _ = flatten_with_names(to_numpy(params["base"]))
    for name in want:
        np.testing.assert_allclose(
            got[name], want[name], rtol=0.02, atol=0.02,
            err_msg="%s not dequantized-loaded" % name)


def test_lora_under_parallel_mesh():
    """Merge-at-forward must compose with the tp/sp-sharded mesh path
    (adapters are replicated; the delta add follows W's sharding)."""
    from elasticdl_tpu.parallel.mesh import build_mesh

    mesh = build_mesh(tp=2, sp=2)  # 8 virtual devices -> dp=2
    spec = lora.model_spec(rank=2, mesh=mesh, **LM_KW)
    params = spec.init_fn(jax.random.PRNGKey(0))
    toks = make_tokens(2, 16, seed=12)
    out = np.asarray(spec.apply_fn(params, toks, False))
    want = np.asarray(
        tfm.forward(params["base"], toks, spec.config, mesh=mesh))
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-5)


def test_lora_with_chunked_xent_matches_dense_loss():
    """LoRA + chunked cross-entropy (the realistic large-model
    fine-tune config): the chunked loss path hands MERGED params to
    the head matmul inside loss_fn, so chunked == dense loss under
    adapters."""
    from elasticdl_tpu.worker.collective_trainer import CollectiveTrainer

    toks = make_tokens(4, 16, seed=20)
    losses = {}
    for chunk in (0, 8):
        spec = lora.model_spec(rank=2, xent_chunk=chunk, **LM_KW)
        trainer = CollectiveTrainer(spec, batch_size=4)
        loss, _ = trainer.train_minibatch(toks, toks)
        losses[chunk] = float(loss)
    assert abs(losses[0] - losses[8]) < 1e-5, losses


def test_lora_on_moe_config():
    """MoE base: the default attention targets adapt fine (zero-delta
    == base), and targeting a 4-D expert matrix fails with the
    rank-explaining error rather than a shape surprise."""
    spec = lora.model_spec(rank=2, moe_experts=2, **LM_KW)
    params = spec.init_fn(jax.random.PRNGKey(0))
    toks = make_tokens(2, 8, seed=30)
    got = np.asarray(spec.apply_fn(params, toks, False))
    want = np.asarray(tfm.forward(params["base"], toks, spec.config))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    bad = lora.model_spec(rank=2, moe_experts=2,
                          lora_targets="wq,w_gate", **LM_KW)
    with pytest.raises(ValueError, match="rank-4"):
        bad.init_fn(jax.random.PRNGKey(0))  # raised at adapter init
