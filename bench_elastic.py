"""Elastic recovery benchmark: time from worker preemption to restored
training progress (the BASELINE.json "elastic recovery time after
preempt" metric).

Runs a managed job with the process backend, SIGKILLs a worker mid-run,
and measures:
  - relaunch_secs: preemption -> replacement worker process launched
  - recovery_secs: preemption -> first task completed after the
    preemption (training is demonstrably making progress again)

Control-plane metric: runs on CPU workers; the recovery path is identical
for TPU-VM workers (same state flows).  Prints one JSON line.
"""

import json
import os
import sys
import threading
import time

# Force CPU (not setdefault: the session shell exports
# JAX_PLATFORMS=axon, which would aim the drill workers at the TPU relay
# and hang the control-plane measurement when the relay is wedged).
_PLATFORM = os.environ.get("ELASTICDL_TPU_PLATFORM") or "cpu"
os.environ["ELASTICDL_TPU_PLATFORM"] = _PLATFORM
os.environ["JAX_PLATFORMS"] = _PLATFORM


def run_drill(num_workers=2, records=4096):
    import jax

    jax.config.update("jax_platforms", "cpu")

    from elasticdl_tpu.data.factory import create_data_reader
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.master.worker_manager import (
        ProcessWorkerBackend,
        WorkerManager,
    )
    from elasticdl_tpu.proto import elastic_pb2 as pb

    reader = create_data_reader("synthetic_mnist:%d" % records,
                                records_per_shard=128)
    task_manager = TaskManager(
        training_shards=reader.create_shards(), records_per_task=128,
        num_epochs=2,
    )
    worker_args = [
        "--model_zoo", "mnist", "--data_origin",
        "synthetic_mnist:%d" % records, "--batch_size", "32",
        "--num_minibatches_per_task", "4", "--num_epochs", "2",
    ]
    worker_manager = WorkerManager(
        ProcessWorkerBackend(worker_args=worker_args),
        num_workers=num_workers,
    )
    master = Master(task_manager, worker_manager=worker_manager)

    events = {}
    launch_times = []
    worker_manager.add_start_callback(
        lambda wid: launch_times.append((wid, time.perf_counter()))
    )

    master.prepare()
    runner = threading.Thread(target=master.run, daemon=True)
    runner.start()

    # wait until training is underway (a few tasks done)
    deadline = time.time() + 180
    while time.time() < deadline:
        if task_manager.counts()["completed"][pb.TRAINING] >= 2:
            break
        time.sleep(0.2)

    victim = worker_manager.live_worker_ids()[0]
    completed_before = task_manager.counts()["completed"][pb.TRAINING]
    t_kill = time.perf_counter()
    worker_manager.preempt_worker(victim, force=True)

    # relaunch time: first launch event after the kill
    relaunch_secs = None
    recovery_secs = None
    deadline = time.time() + 180
    while time.time() < deadline:
        if relaunch_secs is None:
            later = [t for wid, t in launch_times if t > t_kill]
            if later:
                relaunch_secs = later[0] - t_kill
        counts = task_manager.counts()
        if counts["completed"][pb.TRAINING] > completed_before:
            recovery_secs = time.perf_counter() - t_kill
            break
        time.sleep(0.05)

    runner.join(timeout=240)
    master.stop()
    counts = task_manager.counts()
    return {
        "metric": "elastic_recovery_time",
        "value": round(recovery_secs, 3) if recovery_secs else None,
        "unit": "seconds",
        "detail": {
            "relaunch_secs": round(relaunch_secs, 3)
            if relaunch_secs else None,
            "tasks_failed_permanently": counts["failed"][pb.TRAINING],
            "tasks_completed": counts["completed"][pb.TRAINING],
            "note": "preemption -> first task completed afterwards; "
                    "CPU workers (control-plane metric)",
        },
    }


if __name__ == "__main__":
    print(json.dumps(run_drill()))
    sys.exit(0)
