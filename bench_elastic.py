"""Elastic recovery benchmark: time from worker preemption to restored
training progress (the BASELINE.json "elastic recovery time after
preempt" metric).

Runs a managed job with the process backend, SIGKILLs a worker mid-run,
and measures:
  - relaunch_secs: preemption -> replacement worker process launched
  - recovery_secs: preemption -> first task completed after the
    preemption (training is demonstrably making progress again)

Control-plane metric: runs on CPU workers; the recovery path is identical
for TPU-VM workers (same state flows).  Prints one JSON line.
"""

import json
import os
import sys
import threading
import time

# Force CPU (not setdefault: the session shell exports
# JAX_PLATFORMS=axon, which would aim the drill workers at the TPU relay
# and hang the control-plane measurement when the relay is wedged).
_PLATFORM = os.environ.get("ELASTICDL_TPU_PLATFORM") or "cpu"
os.environ["ELASTICDL_TPU_PLATFORM"] = _PLATFORM
os.environ["JAX_PLATFORMS"] = _PLATFORM


def run_drill(num_workers=2, records=4096, worker_env=None,
              deadline_secs=180, extra_worker_args=None,
              with_rendezvous=False, wait_complete=False):
    """One preemption drill.  ``worker_env`` overrides the worker
    process env — the TPU legs use it to aim workers at the real chip
    and at a persistent compilation cache (see ``main``).
    ``extra_worker_args``: appended worker flags — the fused leg passes
    ``--fused_steps`` to drill preemption against the windowed hot
    loop (worker/fused_driver.py).

    ``with_rendezvous``: attach a RendezvousServer so collective-mode
    workers get membership epochs (no coordinator factory — each
    worker keeps a process-local device mesh, which is what this
    container's jax supports, but every join/leave commits a real
    epoch, so the preemption exercises snapshot -> rebuild ->
    re-partition on the survivors).  ``wait_complete``: after recovery
    is measured, wait for the JOB to finish and account every record —
    the zero-lost/zero-double-count gate of the zero1 churn leg."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # master stays on CPU

    from elasticdl_tpu.data.factory import create_data_reader
    from elasticdl_tpu.master.master import Master
    from elasticdl_tpu.master.rendezvous import RendezvousServer
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.master.worker_manager import (
        ProcessWorkerBackend,
        WorkerManager,
    )
    from elasticdl_tpu.proto import elastic_pb2 as pb

    records_per_task = 128
    num_epochs = 2
    reader = create_data_reader("synthetic_mnist:%d" % records,
                                records_per_shard=records_per_task)
    task_manager = TaskManager(
        training_shards=reader.create_shards(),
        records_per_task=records_per_task,
        num_epochs=num_epochs,
    )
    worker_args = [
        "--model_zoo", "mnist", "--data_origin",
        "synthetic_mnist:%d" % records, "--batch_size", "32",
        "--num_minibatches_per_task", "4", "--num_epochs",
        str(num_epochs),
    ] + list(extra_worker_args or [])
    worker_manager = WorkerManager(
        ProcessWorkerBackend(worker_args=worker_args,
                             env=worker_env or {}),
        num_workers=num_workers,
    )
    rendezvous = (
        RendezvousServer(grace_secs=1.0) if with_rendezvous else None
    )
    master = Master(task_manager, worker_manager=worker_manager,
                    rendezvous_server=rendezvous)

    events = {}
    launch_times = []
    worker_manager.add_start_callback(
        lambda wid: launch_times.append((wid, time.perf_counter()))
    )

    master.prepare()
    runner = threading.Thread(target=master.run, daemon=True)
    runner.start()

    # wait until training is underway (a few tasks done)
    deadline = time.time() + deadline_secs
    while time.time() < deadline:
        if task_manager.counts()["completed"][pb.TRAINING] >= 2:
            break
        time.sleep(0.2)

    victim = worker_manager.live_worker_ids()[0]
    completed_before = task_manager.counts()["completed"][pb.TRAINING]
    t_kill = time.perf_counter()
    worker_manager.preempt_worker(victim, force=True)

    # relaunch time: first launch event after the kill
    relaunch_secs = None
    recovery_secs = None
    deadline = time.time() + deadline_secs
    while time.time() < deadline:
        if relaunch_secs is None:
            later = [t for wid, t in launch_times if t > t_kill]
            if later:
                relaunch_secs = later[0] - t_kill
        counts = task_manager.counts()
        if counts["completed"][pb.TRAINING] > completed_before:
            recovery_secs = time.perf_counter() - t_kill
            break
        time.sleep(0.05)

    expected_tasks = -(-records // records_per_task) * num_epochs
    records_ok = None
    if wait_complete:
        # Run the job to the end and account every record: the
        # preempted worker's in-flight task must be requeued (never
        # lost) and its completed batches never double-reported, so
        # exactly the expected task count completes — no more (a
        # double count would finish a task twice), no less.
        deadline = time.time() + deadline_secs
        while time.time() < deadline:
            counts = task_manager.counts()
            done = (counts["completed"][pb.TRAINING]
                    + counts["failed"][pb.TRAINING])
            if counts["todo"] == 0 and counts["doing"] == 0 and (
                done >= expected_tasks
            ):
                break
            time.sleep(0.2)
        counts = task_manager.counts()
        records_ok = (
            counts["completed"][pb.TRAINING] == expected_tasks
            and counts["failed"][pb.TRAINING] == 0
        )

    master.stop()
    runner.join(timeout=30)
    counts = task_manager.counts()
    out = {
        "recovery_secs": round(recovery_secs, 3) if recovery_secs
        else None,
        "relaunch_secs": round(relaunch_secs, 3) if relaunch_secs
        else None,
        "tasks_failed_permanently": counts["failed"][pb.TRAINING],
        "tasks_completed": counts["completed"][pb.TRAINING],
    }
    if wait_complete:
        out["tasks_expected"] = expected_tasks
        out["all_records_accounted"] = records_ok
    return out


def _reap_orphan_workers(marker):
    """Workers of a SIGKILLed master are re-parented to init; find any
    stragglers by the drill's distinctive data-origin arg in
    /proc/*/cmdline and kill them (best effort, drill hygiene)."""
    import signal

    reaped = 0
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == os.getpid():
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as fh:
                cmd = fh.read().decode("utf-8", "replace")
        except OSError:
            continue
        if marker in cmd and "elasticdl_tpu.worker.main" in cmd:
            try:
                os.kill(int(pid), signal.SIGKILL)
                reaped += 1
            except OSError:
                pass
    return reaped


def run_master_kill_drill(records=4160, deadline_secs=300):
    """SIGKILL the MASTER mid-training, restart it from the job-state
    journal, and prove the job completes with exact task accounting.

    Phase 1 master launches 2 process workers and journals to a temp
    dir.  The kill orphans the workers; their outage-riding clients
    (utils/retry.py) keep retrying against the fixed port.  Phase 2
    relaunches the master with --num_workers 0 on the SAME port: it
    replays the journal, requeues the in-flight tasks, and the
    surviving workers reconnect WITHOUT a process restart.  Measures
    recovery_secs (kill -> first task completion after restart,
    observed by replaying the live journal) and asserts completed ==
    expected with zero permanent failures — a double-counted record
    would overshoot, a lost one would hang/undershoot.

    Tracing gate (docs/observability.md): every process runs with
    $ELASTICDL_TRACE_DIR armed; the surviving workers' and the
    restarted master's flight-recorder dumps must stitch into ONE
    connected trace covering kill (worker-side rpc_retry events in the
    outage window) -> recovery (master #2's journal replay, linked via
    link_trace) -> the first post-recovery task completion
    (restart-stamped task.completed) — ``trace_connected`` below."""
    import shutil
    import signal
    import subprocess
    import tempfile

    from elasticdl_tpu.master.journal import replay_journal
    from elasticdl_tpu.proto import elastic_pb2 as pb
    from elasticdl_tpu.utils import tracing
    from elasticdl_tpu.utils.grpc_utils import find_free_port

    records_per_task = 32 * 4
    num_epochs = 2
    expected_tasks = -(-records // records_per_task) * num_epochs
    data_origin = "synthetic_mnist:%d" % records
    jdir = tempfile.mkdtemp(prefix="edl_journal_")
    tdir = os.path.join(jdir, "traces")
    port = find_free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu", ELASTICDL_TPU_PLATFORM="cpu",
        # Orphaned workers must die promptly if the job wedges; 45 s
        # comfortably covers the master restart gap.
        ELASTICDL_RPC_DEADLINE_SECS="45",
        # Flight-recorder dumps on exit: workers + master #2 land here
        # (master #1 is SIGKILLed — by definition it leaves no dump;
        # the survivors' rings reconstruct the incident).
        ELASTICDL_TRACE_DIR=tdir,
    )
    base_cmd = [
        sys.executable, "-m", "elasticdl_tpu.master.main",
        "--model_zoo", "mnist", "--data_origin", data_origin,
        "--batch_size", "32", "--num_minibatches_per_task", "4",
        "--num_epochs", str(num_epochs),
        "--journal_dir", jdir, "--port", str(port),
    ]

    def completed_training():
        state = replay_journal(jdir)
        if state is None:
            return 0
        return state.completed_counts.get(int(pb.TRAINING), 0)

    out = {"tasks_expected": expected_tasks}
    master2 = None
    master1 = subprocess.Popen(base_cmd + ["--num_workers", "2"],
                               env=env)
    try:
        deadline = time.time() + deadline_secs
        while time.time() < deadline and completed_training() < 3:
            time.sleep(0.25)
        t_kill = time.perf_counter()
        master1.send_signal(signal.SIGKILL)
        master1.wait(timeout=30)
        # Baseline AFTER the master is verifiably dead: the journal is
        # final, so any later increase can only come from master #2.
        # (Reading it before the SIGKILL lands races a concurrent done
        # flush and fakes a near-zero recovery time.)
        done_at_kill = completed_training()
        out["tasks_done_at_kill"] = done_at_kill

        # Restart from the journal; the orphaned workers reconnect.
        master2 = subprocess.Popen(base_cmd + ["--num_workers", "0"],
                                   env=env)
        recovery_secs = None
        deadline = time.time() + deadline_secs
        while time.time() < deadline:
            if recovery_secs is None and (
                completed_training() > done_at_kill
            ):
                recovery_secs = time.perf_counter() - t_kill
            if master2.poll() is not None:
                break
            time.sleep(0.25)
        if master2.poll() is None:
            master2.kill()
            master2.wait(timeout=10)
            out["error"] = "restarted master did not finish in time"
        out["recovery_secs"] = (
            round(recovery_secs, 3) if recovery_secs else None
        )
        out["master2_exit_code"] = master2.poll()
        state = replay_journal(jdir)
        completed = state.completed_counts.get(int(pb.TRAINING), 0)
        failed = sum(state.failed_counts.values())
        out["tasks_completed"] = completed
        out["tasks_failed_permanently"] = failed
        out["restarts_journaled"] = state.restarts
        # Exact accounting: every task completes exactly once across
        # the crash (the journal's done-set can't double-count).
        out["all_records_accounted"] = (
            completed == expected_tasks and failed == 0
            and master2.poll() == 0
        )
        out["journal_bytes"] = os.path.getsize(
            os.path.join(jdir, "job.journal")
        )
        # Trace gate: the orphaned workers exit (and dump) shortly
        # after master #2 reports the job done — wait briefly for
        # master #2 + both workers' rings (an idle worker that rides
        # its WAIT poll into the reaper leaves no dump; the gate only
        # needs ONE worker ring plus the master's).
        deadline = time.time() + 10
        while time.time() < deadline:
            dumps = (
                [] if not os.path.isdir(tdir) else
                [f for f in os.listdir(tdir)
                 if f.endswith(".trace.json")]
            )
            if len(dumps) >= 2:
                break
            time.sleep(0.25)
        events = tracing.load_dumps(tdir)
        components = tracing.trace_components(events)

        def _connected(component):
            names = {e["name"] for e in component}
            return (
                {"rpc_retry", "journal.replayed",
                 "task.completed"} <= names
                and any(e.get("restart") for e in component
                        if e["name"] == "task.completed")
            )

        out["trace_dumps"] = len(dumps)
        out["trace_events"] = len(events)
        out["trace_connected"] = any(
            _connected(c) for c in components
        )
    finally:
        for proc in (master1, master2):
            if proc is not None and proc.poll() is None:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
        reaped = _reap_orphan_workers(data_origin)
        if reaped:
            out["orphan_workers_reaped"] = reaped
        shutil.rmtree(jdir, ignore_errors=True)
    return out


def _scan_procs(marker, module):
    """Pids whose cmdline holds both ``marker`` and ``module`` —
    (pid, cmdline) pairs, the drill's view of a managed job's
    subprocess tree."""
    found = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) == os.getpid():
            continue
        try:
            with open("/proc/%s/cmdline" % pid, "rb") as fh:
                cmd = fh.read().replace(b"\x00", b" ").decode(
                    "utf-8", "replace"
                )
        except OSError:
            continue
        if marker in cmd and module in cmd:
            found.append((int(pid), cmd))
    return found


def run_ps_kill_drill(records=1024, deadline_secs=300):
    """SIGKILL one PS SHARD mid-training (the worker->PS direction of
    the recovery drills, docs/ps_recovery.md): PSManager relaunches it
    with a bumped restart generation and restore from the newest
    COMMITTED cross-shard checkpoint; the workers ride the outage on
    the same port through the PSClient retry policy — WITHOUT a worker
    restart — detect the generation change, drop fenced in-flight
    pushes, and reconcile.  Gates:

      - shard relaunched with --generation 2 + restore (cmdline-proved)
      - restored version was a COMMITTED label (consistent across all
        shards — CheckpointSaver.is_valid_version)
      - zero worker relaunches (outage ridden, not died through)
      - exact record accounting: completed == expected, 0 failed
      - every push stamped by the dead incarnation that reached the new
        one was generation-fenced (rejected, never applied) — counted
        from the servicer's fencing log lines

    Additionally arms --ps_rpc_fault_spec so the run ALSO rides
    deterministic injected worker->PS faults (every 31st dense pull
    answers UNAVAILABLE) through the same retry plumbing.  A fault
    spec that fails to parse kills every shard at startup, so the
    drill doubles as a grammar conformance check."""
    import re
    import shutil
    import signal
    import subprocess
    import tempfile

    from elasticdl_tpu.master.journal import replay_journal
    from elasticdl_tpu.proto import elastic_pb2 as pb
    from elasticdl_tpu.utils.checkpoint import CheckpointSaver
    from elasticdl_tpu.utils.grpc_utils import find_free_port

    records_per_task = 32 * 4
    num_epochs = 2
    expected_tasks = -(-records // records_per_task) * num_epochs
    data_origin = "synthetic_ctr:%d" % records
    jdir = tempfile.mkdtemp(prefix="edl_psjournal_")
    ckpt = tempfile.mkdtemp(prefix="edl_psckpt_")
    port = find_free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu", ELASTICDL_TPU_PLATFORM="cpu",
        # The outage window is PSManager's relaunch (~seconds); 45 s
        # of riding covers it with margin while bounding a wedged run.
        ELASTICDL_RPC_DEADLINE_SECS="45",
    )
    cmd = [
        sys.executable, "-m", "elasticdl_tpu.master.main",
        "--model_zoo", "deepfm", "--data_origin", data_origin,
        "--batch_size", "32", "--num_minibatches_per_task", "4",
        "--num_epochs", str(num_epochs),
        "--distribution_strategy", "ps", "--num_ps", "2",
        "--num_workers", "2",
        "--checkpoint_dir", ckpt, "--checkpoint_steps", "8",
        "--journal_dir", jdir, "--port", str(port),
        # Pipelined pushes + embedding prefetch ON so the kill lands
        # against in-flight state the reconcile must drop.
        "--async_push_window", "2", "--get_model_steps", "2",
        # Worker->PS deterministic fault injection riding alongside
        # the kill (docs/master_recovery.md grammar).
        "--ps_rpc_fault_spec",
        "pull_dense_parameters:every=31,code=UNAVAILABLE",
    ]

    def completed_training():
        state = replay_journal(jdir)
        if state is None:
            return 0
        return state.completed_counts.get(int(pb.TRAINING), 0)

    out = {"tasks_expected": expected_tasks}
    log_path = os.path.join(jdir, "drill.log")
    log_fh = open(log_path, "w")
    master = subprocess.Popen(cmd, env=env, stdout=log_fh,
                              stderr=subprocess.STDOUT, text=True)
    try:
        saver = CheckpointSaver(ckpt)
        # Labels observed committed at ANY point during the run: the
        # restored-label gate must judge against commit state around
        # restore time, not after end-of-job GC pruned old labels.
        seen_committed = set()
        deadline = time.time() + deadline_secs
        # Kill only after a checkpoint label COMMITTED across both
        # shards (else the relaunch legitimately restores nothing) and
        # training demonstrably progresses.
        while time.time() < deadline:
            seen_committed.update(saver.versions())
            if completed_training() >= 3 and seen_committed:
                break
            time.sleep(0.25)
        shards = _scan_procs(ckpt, "elasticdl_tpu.ps.server")
        victim = next((pid for pid, cmd_ in shards
                       if "--ps_id 0" in cmd_), None)
        workers_before = sorted(
            pid for pid, _ in _scan_procs(data_origin,
                                          "elasticdl_tpu.worker.main")
        )
        out["error"] = None
        if victim is None:
            out["error"] = "PS shard 0 process not found"
            return out
        done_baseline = completed_training()
        t_kill = time.perf_counter()
        os.kill(victim, signal.SIGKILL)

        relaunch_secs = None
        recovery_secs = None
        deadline = time.time() + deadline_secs
        while time.time() < deadline:
            if relaunch_secs is None:
                for pid, cmd_ in _scan_procs(
                    ckpt, "elasticdl_tpu.ps.server"
                ):
                    if pid != victim and "--ps_id 0" in cmd_:
                        relaunch_secs = time.perf_counter() - t_kill
                        out["relaunch_cmdline_ok"] = (
                            "--generation 2" in cmd_
                            and "--checkpoint_dir_for_init" in cmd_
                        )
            if recovery_secs is None and (
                completed_training() > done_baseline
            ):
                recovery_secs = time.perf_counter() - t_kill
            seen_committed.update(saver.versions())
            if master.poll() is not None:
                break
            time.sleep(0.25)
        if master.poll() is None:
            master.kill()
            master.wait(timeout=10)
            out["error"] = "job did not finish in time"
        out["relaunch_secs"] = (
            round(relaunch_secs, 3) if relaunch_secs else None
        )
        out["recovery_secs"] = (
            round(recovery_secs, 3) if recovery_secs else None
        )
        state = replay_journal(jdir)
        completed = state.completed_counts.get(int(pb.TRAINING), 0)
        failed = sum(state.failed_counts.values())
        out["tasks_completed"] = completed
        out["tasks_failed_permanently"] = failed
        log_fh.flush()
        with open(log_path) as fh:
            log = fh.read()
        # Outage ridden, not died through: no worker was ever
        # relaunched (the manager logs every relaunch decision).
        out["worker_relaunches"] = log.count("relaunch=True")
        out["workers_at_kill"] = len(workers_before)
        # Restore consistency: the relaunched shard logged the version
        # it restored; that label must be a COMMITTED (all-shard) one.
        restored = re.findall(r"restored PS shard 0 from version (\d+)",
                              log)
        out["restored_version"] = (
            int(restored[-1]) if restored else None
        )
        out["restored_version_committed"] = bool(
            restored and int(restored[-1]) in seen_committed
        )
        # Fencing: every dead-incarnation push that reached the new
        # shard was rejected (servicer logs each), and the workers
        # reconciled (dropped pipelined pushes + re-pulled).
        out["fenced_pushes"] = log.count(
            "rejecting gradients stamped by generation"
        )
        out["worker_reconciles"] = log.count("reconciled PS restart")
        out["injected_faults_ridden"] = log.count(
            "fault injection: aborting"
        )
        out["all_records_accounted"] = (
            completed == expected_tasks and failed == 0
            and master.poll() == 0
            and out["worker_relaunches"] == 0
            and out["restored_version_committed"]
            and out.get("relaunch_cmdline_ok") is True
            and out["error"] is None
        )
        if out["error"] is None:
            del out["error"]
    finally:
        if master.poll() is None:
            master.kill()
            try:
                master.wait(timeout=10)
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
        log_fh.close()
        _reap_orphan_workers(data_origin)
        for pid, _ in _scan_procs(ckpt, "elasticdl_tpu.ps.server"):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        shutil.rmtree(jdir, ignore_errors=True)
        shutil.rmtree(ckpt, ignore_errors=True)
    return out


def run_multitenant_drill(records_a=1024, records_b=4096,
                          deadline_secs=300):
    """The multi-tenant scheduler drill (docs/scheduler.md): TWO jobs
    over ONE shared 4-worker pool, with a controller-driven resize and
    a master SIGKILL landing MID-RESIZE.

    Topology: jobA (small) and jobB (larger) are admitted together and
    the pool splits 2/2.  jobA finishes first; the resize controller
    reclaims its workers one per tick (each move a journaled, traced
    decision).  The drill SIGKILLs the master the moment the FIRST
    move's ``sched`` record lands in the scheduler journal — the
    decision is durable, the drained worker's re-register is not — and
    restarts it with ``--num_workers 0`` on the same port.  The replay
    must recover the assignment map exactly; the worker still parked
    on finished jobA then gets a LIVE post-restart resize decision,
    whose trace must stitch to the worker's re-register + in-place
    pipeline rebuild.  Gates:

      - both jobs complete with exact per-job record accounting
        (per-job journal namespaces, ``all_records_accounted`` each)
      - ZERO worker process restarts: the 4 pool pids at kill time are
        the only worker pids the drill ever observes
      - >= 1 controller-driven resize (``sched`` assign with prev != 0)
      - trace connectivity: one component holds the resize decision
        (``sched.resize``), the drained worker's re-register
        (``sched.worker_reassigned``, link_trace) and the worker's
        in-place rebuild (``worker.job_switch``)
      - STRAGGLER gate (ISSUE 14): worker 1 is DELIBERATELY throttled
        (ELASTICDL_STEP_THROTTLE_SPEC) — the restarted master's
        straggler sweep must flag it (observed on /status within the
        drill window, or post-hoc via the journal-independent trace
        dump), and the default ``value(straggler_workers) < 1`` SLO
        rule must land an ``slo.breach`` event in the master's flight
        recorder + show on /alertz."""
    import shutil
    import signal
    import subprocess
    import tempfile

    from elasticdl_tpu.master.journal import (
        journal_path,
        replay_journal,
        scan_frames,
    )
    from elasticdl_tpu.proto import elastic_pb2 as pb
    from elasticdl_tpu.utils import tracing
    from elasticdl_tpu.utils.grpc_utils import find_free_port

    records_per_task = 32 * 4
    expected = {
        "jobA": -(-records_a // records_per_task),
        "jobB": -(-records_b // records_per_task),
    }
    # Template data origin: distinctive marker for /proc scans; differs
    # from both jobs so every worker exercises the handshake rebuild.
    template_origin = "synthetic_mnist:1408"
    jdir = tempfile.mkdtemp(prefix="edl_mtjournal_")
    tdir = os.path.join(jdir, "traces")
    jobs_path = os.path.join(jdir, "jobs.json")
    with open(jobs_path, "w") as fh:
        json.dump([
            {"name": "jobA", "data_origin":
             "synthetic_mnist:%d" % records_a,
             "min_workers": 1, "max_workers": 3, "weight": 1.0},
            {"name": "jobB", "data_origin":
             "synthetic_mnist:%d" % records_b,
             "min_workers": 1, "max_workers": 4, "weight": 1.0},
        ], fh)
    port = find_free_port()
    status_port = find_free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu", ELASTICDL_TPU_PLATFORM="cpu",
        ELASTICDL_RPC_DEADLINE_SECS="45",
        ELASTICDL_TRACE_DIR=tdir,
        # Straggler staging: worker 1 (one member of the shared pool,
        # targeted by id through the inherited env) sleeps 500 ms per
        # step — ~4x this rig's ~170 ms per-step-loop CPU mnist step,
        # a GROSS straggler that clears the 2.0x ratio bar by a full
        # log bucket (the p50 estimate quantizes at ~2.15x per
        # bucket) while still stepping fast enough to fill two
        # 4-sample sweep windows before its job drains.
        ELASTICDL_STEP_THROTTLE_SPEC="1:500",
    )
    base_cmd = [
        sys.executable, "-m", "elasticdl_tpu.master.main",
        "--jobs_spec", jobs_path,
        "--model_zoo", "mnist", "--data_origin", template_origin,
        "--batch_size", "32", "--num_minibatches_per_task", "4",
        "--num_epochs", "1",
        "--journal_dir", jdir, "--port", str(port),
        "--sched_cadence_secs", "0.5",
        "--status_port", str(status_port),
    ]

    def _http_json(path, timeout=2.0):
        import urllib.request

        try:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d%s" % (status_port, path),
                    timeout=timeout) as resp:
                return json.loads(resp.read())
        except Exception:  # noqa: BLE001 — master between lives
            return None
    sched_dir = os.path.join(jdir, "sched")

    def sched_moves():
        """Resize decisions journaled so far: assign records whose
        ``prev`` names a real job — a cross-job MOVE, not a pool
        registration."""
        path = journal_path(sched_dir)
        if not os.path.exists(path):
            return 0
        with open(path, "rb") as fh:
            data = fh.read()
        return sum(
            1 for rec, _ in scan_frames(data)
            if rec.get("ev") == "sched" and rec.get("op") == "assign"
            and rec.get("prev")
        )

    def job_completed(job_dir):
        state = replay_journal(os.path.join(jdir, job_dir))
        if state is None:
            return 0, 0
        return (state.completed_counts.get(int(pb.TRAINING), 0),
                sum(state.failed_counts.values()))

    out = {"tasks_expected": dict(expected)}
    log_path = os.path.join(jdir, "drill.log")
    log_fh = open(log_path, "w")
    master2 = None
    master1 = subprocess.Popen(base_cmd + ["--num_workers", "4"],
                               env=env, stdout=log_fh,
                               stderr=subprocess.STDOUT, text=True)
    worker_pids = set()

    def scan_workers():
        pids = {
            pid for pid, _ in _scan_procs(
                template_origin, "elasticdl_tpu.worker.main")
        }
        worker_pids.update(pids)
        return pids

    try:
        # Wait for the mid-resize moment: jobA drains, the controller's
        # FIRST reclaim decision lands in the scheduler journal.
        deadline = time.time() + deadline_secs
        while time.time() < deadline and sched_moves() < 1:
            scan_workers()
            time.sleep(0.1)
        pids_at_kill = scan_workers()
        out["workers_at_kill"] = len(pids_at_kill)
        out["moves_at_kill"] = sched_moves()
        t_kill = time.perf_counter()
        master1.send_signal(signal.SIGKILL)
        master1.wait(timeout=30)
        if out["moves_at_kill"] < 1:
            out["error"] = "no resize decision before deadline"
            return out

        master2 = subprocess.Popen(base_cmd + ["--num_workers", "0"],
                                   env=env, stdout=log_fh,
                                   stderr=subprocess.STDOUT, text=True)
        recovery_secs = None
        straggler_on_status = False
        breach_on_alertz = False
        deadline = time.time() + deadline_secs
        while time.time() < deadline:
            scan_workers()
            done_b, _ = job_completed("job-02")
            if recovery_secs is None and done_b >= expected["jobB"]:
                recovery_secs = time.perf_counter() - t_kill
            if not straggler_on_status:
                # The throttled worker on the live /status surface:
                # the restarted master's sweeps re-flag it from fresh
                # state; once sustained it STAYS flagged (un-flagging
                # takes a healthy judged window), so this poll is not
                # racing a transient.
                status = _http_json("/status")
                for job in (status or {}).get("jobs", {}).values():
                    workers = (job.get("telemetry") or {}).get(
                        "workers", {})
                    if any(t.get("straggler")
                           for t in workers.values()):
                        straggler_on_status = True
            if not breach_on_alertz:
                alertz = _http_json("/alertz")
                if alertz and "stragglers" in alertz.get(
                        "breaching", []):
                    breach_on_alertz = True
            if master2.poll() is not None:
                break
            time.sleep(0.25)
        if master2.poll() is None:
            master2.kill()
            master2.wait(timeout=10)
            out["error"] = "restarted master did not finish in time"
        out["master2_exit_code"] = master2.poll()
        out["recovery_secs"] = (
            round(recovery_secs, 3) if recovery_secs else None
        )

        # Per-job exact accounting from each job's journal namespace.
        accounted = {}
        for job_dir, name in (("job-01", "jobA"), ("job-02", "jobB")):
            completed, failed = job_completed(job_dir)
            accounted[name] = (
                completed == expected[name] and failed == 0
            )
            out["tasks_completed_%s" % name] = completed
            out["tasks_failed_%s" % name] = failed
        out["resize_moves_total"] = sched_moves()
        sched_state = replay_journal(sched_dir)
        out["restarts_journaled"] = (
            sched_state.restarts if sched_state else 0
        )

        # Zero worker process restarts: the pool pids at kill time are
        # the only worker pids ever observed, and the master log holds
        # no relaunch decision.
        log_fh.flush()
        with open(log_path) as fh:
            log = fh.read()
        out["worker_relaunches"] = log.count("relaunch=True")
        out["worker_pids_observed"] = len(worker_pids)
        zero_restarts = (
            out["worker_relaunches"] == 0
            and worker_pids == pids_at_kill
            and len(pids_at_kill) == 4
        )
        out["zero_worker_restarts"] = zero_restarts

        # Trace gate: master #2's live resize decision + the drained
        # worker's re-register + its in-place pipeline rebuild in ONE
        # connected component (master #1's ring died with it — the
        # post-restart decision is the one that must stitch).
        deadline = time.time() + 10
        while time.time() < deadline:
            dumps = (
                [] if not os.path.isdir(tdir) else
                [f for f in os.listdir(tdir)
                 if f.endswith(".trace.json")]
            )
            if len(dumps) >= 2:
                break
            time.sleep(0.25)
        events = tracing.load_dumps(tdir)
        components = tracing.trace_components(events)
        required = {"sched.resize", "sched.worker_reassigned",
                    "worker.job_switch"}
        out["trace_dumps"] = len(dumps)
        out["trace_events"] = len(events)
        out["trace_connected"] = any(
            required <= {e["name"] for e in c} for c in components
        )

        # Straggler gate (ISSUE 14): flagged live on /status +
        # breaching on /alertz, AND the slo.breach / worker.straggler
        # events in the master's dumped flight recorder.
        names = {e.get("name") for e in events}
        out["straggler_on_status"] = straggler_on_status
        out["slo_breach_on_alertz"] = breach_on_alertz
        out["slo_breach_in_recorder"] = "slo.breach" in names
        out["straggler_event_in_recorder"] = (
            "worker.straggler" in names)
        straggler_gate = (
            straggler_on_status and breach_on_alertz
            and out["slo_breach_in_recorder"]
            and out["straggler_event_in_recorder"]
        )
        out["straggler_detected"] = straggler_gate

        out["all_records_accounted"] = (
            all(accounted.values())
            and master2.poll() == 0
            and zero_restarts
            and out["resize_moves_total"] >= 1
            and out["trace_connected"]
            and straggler_gate
        )
        out["per_job_accounted"] = accounted
    finally:
        for proc in (master1, master2):
            if proc is not None and proc.poll() is None:
                proc.kill()
                try:
                    proc.wait(timeout=10)
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
        log_fh.close()
        reaped = _reap_orphan_workers(template_origin)
        if reaped:
            out["orphan_workers_reaped"] = reaped
        shutil.rmtree(jdir, ignore_errors=True)
    return out


def main():
    """Three legs (VERDICT r4 #3 — BASELINE.json metric #3 and SURVEY
    §7's named hard part, re-init -> re-shard -> re-compile):

    cpu        control-plane drill, 2 CPU workers (state flows only)
    tpu_cold   1 TPU worker, EMPTY compilation cache: preemption ->
               replacement boots, re-inits the relay backend,
               RE-COMPILES the train step, completes a task
    tpu_warm   same with the persistent cache already populated (by
               tpu_cold) — the production recovery path

    The TPU legs are probe-gated (a wedged relay costs one <=90 s
    probe, never a full drill) and each runs in its own watchdog'd
    subprocess.  Headline value = tpu_warm recovery when measured
    (else cpu), with every leg in the detail.
    """
    import shutil
    import subprocess

    budget = int(os.environ.get("ELASTICDL_ELASTIC_BENCH_BUDGET",
                                "900"))
    t0 = time.monotonic()

    def remaining():
        return budget - (time.monotonic() - t0) - 10

    detail = {"platform_legs": {}}
    legs = detail["platform_legs"]
    legs["cpu"] = run_drill()
    legs["cpu"]["note"] = "2 CPU process workers; control-plane cost"
    # Same drill against the fused-step hot loop: preemption must land
    # between windows, flush the in-flight window's progress, and
    # requeue the remainder — recovery and zero-task-loss must match
    # the per-step leg (worker/fused_driver.py semantics).
    legs["cpu_fused"] = run_drill(
        extra_worker_args=["--fused_steps", "4"]
    )
    legs["cpu_fused"]["note"] = (
        "2 CPU process workers, --fused_steps 4: preemption against "
        "the windowed hot loop"
    )
    # ZeRO-1 churn leg: collective workers (each on a process-local
    # 4-device virtual mesh — this container's jax has no multi-proc
    # coordination service, so epochs re-form per-process worlds) with
    # sharded optimizer state and fused windows.  The kill lands a
    # real rendezvous epoch on the survivor: snapshot gathers its live
    # zero1 shards, rebuild re-shards them, and the job then runs to
    # completion with every record accounted exactly once.  (The
    # trajectory-bitwise-through-resize assertion lives in
    # bench_zero.py's in-process churn, where both runs share one
    # param state.)
    legs["cpu_zero1"] = run_drill(
        extra_worker_args=[
            "--distribution_strategy", "collective",
            "--zero1", "true", "--fused_steps", "4",
        ],
        worker_env={
            "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        },
        with_rendezvous=True,
        wait_complete=True,
    )
    legs["cpu_zero1"]["note"] = (
        "2 CPU collective workers, --zero1 --fused_steps 4, "
        "4-device process-local meshes: preemption re-forms the "
        "world with live sharded optimizer state; job runs to "
        "completion with exact record accounting"
    )
    # Master-kill leg: the one component that used to be the SPOF.
    # SIGKILL the MASTER mid-run, restart it from the job-state
    # journal on the same port, orphaned workers ride the outage and
    # reconnect without a process restart (docs/master_recovery.md).
    legs["cpu_master_kill"] = run_master_kill_drill()
    legs["cpu_master_kill"]["note"] = (
        "master SIGKILLed mid-run and restarted from --journal_dir; "
        "2 orphaned CPU workers reconnect via the outage-riding RPC "
        "retry policy; exact task accounting asserted from the "
        "journal (wait_complete-equivalent gate)"
    )
    # PS-shard-kill leg: the worker->PS direction (docs/ps_recovery.md).
    # SIGKILL one PS shard of a pipelined 2-shard PS-mode job; PSManager
    # relaunches it with a bumped restart generation + restore from the
    # committed cross-shard checkpoint; both workers ride the outage on
    # the same port, fence/reconcile, and the job completes with exact
    # accounting — with deterministic worker->PS faults injected on top.
    legs["cpu_ps_kill"] = run_ps_kill_drill()
    legs["cpu_ps_kill"]["note"] = (
        "PS shard 0 SIGKILLed mid-run (2 shards, 2 CPU workers, "
        "--async_push_window 2): relaunch+restore at a committed "
        "checkpoint label, generation fencing rejects dead-incarnation "
        "pushes, zero worker relaunches, exact task accounting"
    )
    # Multi-tenant leg (docs/scheduler.md): 2 jobs over one shared
    # 4-worker pool; the resize controller reclaims the finished job's
    # workers one journaled+traced decision at a time; the master is
    # SIGKILLed MID-RESIZE and restarted from the sched journal — both
    # jobs complete with exact per-job accounting, zero worker process
    # restarts, and the post-restart resize decision stitches to the
    # drained worker's re-register + in-place rebuild in one trace.
    legs["cpu_multitenant"] = run_multitenant_drill()
    legs["cpu_multitenant"]["note"] = (
        "2 jobs / shared 4-worker pool: controller-driven resize, "
        "master SIGKILLed mid-resize and restarted from the scheduler "
        "journal; per-job all_records_accounted, zero worker process "
        "restarts, decision->re-register trace connectivity"
    )

    import bench as _bench  # probe + provenance helpers

    tpu_env_base = {
        # undo this module's CPU pin for the worker processes only
        "ELASTICDL_TPU_PLATFORM": "", "JAX_PLATFORMS": "",
        "ELASTICDL_FUSED_GN": "off",
    }
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache_elastic")
    ok, reason = (False, "skipped: no budget")
    if remaining() > 240:
        # The probe must bypass this module's CPU pin (empty strings
        # undo it for the subprocess) and must have reached a REAL
        # accelerator — "PROBE-OK cpu" is a false positive here.
        stdout, sub_reason = _bench._run_sub(
            ["--probe"], min(90, int(remaining() - 120)),
            env=tpu_env_base,
        )
        if stdout and "PROBE-OK" in stdout and (
            "PROBE-OK cpu" not in stdout
        ):
            ok = True
        else:
            reason = sub_reason or "probe answered from cpu"
    if ok:
        shutil.rmtree(cache_dir, ignore_errors=True)
        for leg, note in (
            ("tpu_cold", "1 TPU worker, empty compile cache: full "
                         "re-init + re-compile on recovery"),
            ("tpu_warm", "1 TPU worker, warm persistent compile "
                         "cache: the production recovery path"),
        ):
            if remaining() < 180:
                legs[leg] = {"error": "skipped, %ds left"
                             % int(remaining())}
                continue
            env = dict(tpu_env_base,
                       JAX_COMPILATION_CACHE_DIR=cache_dir)
            code = (
                "import json, bench_elastic as b; "
                "print('LEG ' + json.dumps(b.run_drill("
                "num_workers=1, worker_env=%r, deadline_secs=300)))"
                % (env,)
            )
            try:
                proc = subprocess.run(
                    [sys.executable, "-c", code],
                    capture_output=True, text=True,
                    timeout=max(60, int(remaining())),
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
                row = next(
                    (json.loads(ln[4:]) for ln in
                     proc.stdout.splitlines() if ln.startswith("LEG ")),
                    None,
                )
                legs[leg] = row or {
                    "error": "no LEG line (exit %d): %s"
                    % (proc.returncode, (proc.stderr or "")[-200:])}
            except subprocess.TimeoutExpired:
                legs[leg] = {"error": "timed out"}
            if isinstance(legs[leg], dict) and "recovery_secs" in (
                legs[leg]
            ):
                legs[leg]["note"] = note
    else:
        legs["tpu"] = {"error": "relay probe failed: %s" % reason}

    warm = legs.get("tpu_warm", {}).get("recovery_secs")
    value = warm if warm is not None else legs["cpu"]["recovery_secs"]
    print(json.dumps({
        "metric": "elastic_recovery_time",
        "value": value,
        "unit": "seconds",
        "vs_baseline": None,
        "detail": dict(
            detail,
            headline_leg="tpu_warm" if warm is not None else "cpu",
            env=_bench._env_snapshot(),
            bench_wall_secs=round(time.monotonic() - t0, 1),
        ),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
